"""Time-compressed replay & incident-scenario harness (ROADMAP item 5).

Backtests the full ingest -> drift -> recalibrate -> refit -> hot-swap
loop: months of recorded or simulated sensor history driven through the
REAL HTTP surface at 100-1000x wall speed, under a composable incident
library, with a per-scenario verdict (detection latency, FP/FN rates
before/after adaptation, adaptation cost, swap pauses, non-200 count).

- ``clock``     — the injectable wall-time seam everything rides on
- ``incidents`` — composable incident primitives + scenario container
- ``scenarios`` — the standard regression library (``make replay``)
- ``engine``    — the replay driver + verdict assembly

Only the clock is imported eagerly: the streaming plane reads the seam
on its import path, so pulling the engine (which imports the server
stack) in at package-init time would be a cycle. Engine/incident names
resolve lazily (PEP 562).
"""

from gordo_components_tpu.replay.clock import (
    SYSTEM_CLOCK,
    Clock,
    ReplayClock,
    SystemClock,
)

__all__ = [
    "Clock",
    "Incident",
    "ReplayClock",
    "ReplayEngine",
    "Scenario",
    "standard_scenarios",
    "SYSTEM_CLOCK",
    "SystemClock",
]

_LAZY = {
    "Incident": "gordo_components_tpu.replay.incidents",
    "Scenario": "gordo_components_tpu.replay.incidents",
    "ReplayEngine": "gordo_components_tpu.replay.engine",
    "standard_scenarios": "gordo_components_tpu.replay.scenarios",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
