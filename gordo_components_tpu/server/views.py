"""HTTP views.

Reference parity: gordo_components/server/views/ (unverified; SURVEY.md §2
"server", §3.2) — REST surface per target:

- ``GET  /gordo/v0/{project}/{target}/healthcheck``
- ``GET  /gordo/v0/{project}/{target}/metadata``
- ``POST /gordo/v0/{project}/{target}/prediction``
- ``POST /gordo/v0/{project}/{target}/anomaly/prediction``
- ``GET  /gordo/v0/{project}/{target}/download-model``

plus collection-level ``GET /gordo/v0/{project}/models``. Implemented on
aiohttp; model compute runs in a thread-pool executor so the event loop
stays responsive while XLA executes.
"""

import asyncio
import functools
import json
import logging
import math
import os
import time
from typing import Any, Dict, Optional

import numpy as np
import pandas as pd
from aiohttp import web

from gordo_components_tpu import __version__, serializer
from gordo_components_tpu.observability.tracing import chrome_trace
from gordo_components_tpu.qos.admission import QosShed
from gordo_components_tpu.qos.classify import classify_meta
from gordo_components_tpu.resilience.deadline import DeadlineExceeded
from gordo_components_tpu.server.bank import EngineOverloaded
from gordo_components_tpu.server.model_io import (
    anomaly_frame_arrays,
    decode_tensor_request_ex,
    encode_anomaly_response,
    encode_prediction_response,
)
from gordo_components_tpu.server.utils import (
    extract_x_y,
    frame_to_dict,
    get_reload_lock,
)
from gordo_components_tpu.utils import parquet_engine_available
from gordo_components_tpu.utils.wire import (
    TENSOR_CONTENT_TYPE,
    WireFormatError,
    encoding_of,
    rows_as_f32,
    unpack_frames,
)

logger = logging.getLogger(__name__)

routes = web.RouteTableDef()


def _collection(request: web.Request):
    return request.app["collection"]


_PARQUET_OK = parquet_engine_available()


def _get_model(request: web.Request):
    target = request.match_info["target"]
    collection = _collection(request)
    try:
        # one-state read: a concurrent /reload swapping the collection
        # must not let the existence check and the metadata lookup see
        # different states
        return collection.entry(target)
    except KeyError:
        raise web.HTTPNotFound(
            text=json.dumps({"error": f"No such model: {target}"}),
            content_type="application/json",
        )


def _bank_engine(request: web.Request):
    """The continuous-batching engine, if the target is bank-resident.

    Under the worker pool each parse loop owns a LOCAL engine over the
    shared bank (server/workers.py) — scoring must use it, never the
    primary's: a cross-loop hop per request costs GIL-switch stalls and
    breaks the local loop's batch coalescing."""
    engine = getattr(request.app, "gordo_engine", None) or request.app.get(
        "bank_engine"
    )
    if engine is not None and request.match_info["target"] in engine.bank:
        return engine
    return None


def _engine_score(engine):
    """The engine's any-loop scoring entry: ``submit`` hops to the
    engine's own loop when the handler runs on a multi-worker parse loop
    (server/workers.py) and is a pure pass-through on the primary loop.
    Test stubs that only implement ``score`` keep working."""
    return getattr(engine, "submit", None) or engine.score


def _quarantine_gate(request: web.Request) -> None:
    """410 Gone (with the recorded reason) for a quarantined target — the
    model EXISTS but was evicted from routing by the failure breaker
    (resilience/quarantine.py); a 404 would lie to the operator and a
    crash-retry loop would keep burning capacity on a poisoned model."""
    quarantine = request.app.get("quarantine")
    target = request.match_info["target"]
    if quarantine is None or target not in quarantine:
        return
    info = quarantine.reason(target) or {}
    raise web.HTTPGone(
        text=json.dumps(
            {
                "error": f"Model {target!r} is quarantined",
                "reason": info.get("reason"),
                "failures": info.get("failures"),
                "since": info.get("since"),
                "clear": f"POST /gordo/v0/{request.match_info['project']}"
                         "/quarantine/clear",
            }
        ),
        content_type="application/json",
    )


def _request_encoding(request: web.Request) -> str:
    """The scoring-POST body encoding, from the content type alone — the
    binary path's OPT-IN switch (the shared rule in utils/wire.py, also
    what the middleware's per-encoding counters classify by)."""
    return encoding_of(request.content_type)


def _note_scoring_result(
    request: web.Request, target: str, X_arr: np.ndarray, values
) -> None:
    """Record a completed score with the quarantine breaker: finite
    output resets the failure streak; non-finite output (NaN/Inf anywhere
    in ``values``) counts as a failure — UNLESS the request's own input
    was non-finite, which is the client's data, not the model's fault.
    The input scan only runs on the (rare) non-finite path. The
    finiteness verdict is also stashed for the goodput ledger: a 200
    carrying NaN scores is wasted work, not goodput.

    ``X_arr`` is the float32 array the model actually scored — the
    handlers validate/convert the request payload ONCE and reuse that
    one array here, instead of the old second
    ``np.asarray(X.values, dtype="float64")`` shadow copy per non-finite
    check (and the verdict is now about the values the model truly saw:
    a float64 payload the float32 cast turned infinite IS non-finite
    input from the model's point of view)."""
    quarantine = request.app.get("quarantine")
    ledger = request.app.get("goodput")
    if quarantine is None and ledger is None:
        return
    arr = np.asarray(values)
    finite = bool(np.all(np.isfinite(arr)))
    input_finite = True
    if not finite:
        input_finite = bool(np.all(np.isfinite(X_arr)))
    if ledger is not None:
        # same exemption the breaker applies: NaN-in-NaN-out is the
        # client's data — the server did its work, so it is not wasted
        # and must not burn the availability budget. Only finite input
        # producing non-finite output counts against goodput.
        request["scores_finite"] = finite or not input_finite
    if quarantine is None:
        return
    if finite:
        quarantine.record_success(target)
    elif input_finite:
        if quarantine.record_failure(
            target, "non-finite scores in model output"
        ):
            _emit_event(
                request.app,
                "quarantine.enter",
                severity="error",
                target=target,
                reason="non-finite scores in model output",
            )


def _note_scoring_error(request: web.Request, target: str, exc: Exception) -> None:
    """Count a scoring exception against the quarantine breaker.
    Input-shape complaints (ValueError/KeyError) are the request's fault,
    not the model's, and a blown deadline is the clock's — neither ever
    counts (expired requests are handled before this is reached; the
    exclusion is belt-and-braces for future call sites)."""
    quarantine = request.app.get("quarantine")
    if quarantine is None or isinstance(
        exc, (ValueError, KeyError, DeadlineExceeded)
    ):
        return
    if quarantine.record_failure(target, f"{type(exc).__name__}: {exc}"):
        _emit_event(
            request.app,
            "quarantine.enter",
            severity="error",
            target=target,
            reason=f"{type(exc).__name__}: {exc}",
        )


def _emit_event(
    app: web.Application, etype: str, severity: str = "info", **attrs
) -> None:
    """Stamp a state transition onto the flight-recorder timeline
    (observability/events.py), tagged with the current bank generation.
    Absent log (apps built before the recorder, bare test apps) = no-op."""
    events = app.get("events")
    if events is not None:
        events.emit(
            etype,
            severity=severity,
            generation=app.get("bank_generation"),
            **attrs,
        )


def _http_overloaded(exc: EngineOverloaded) -> web.HTTPTooManyRequests:
    """429 with a drain-estimate Retry-After for a shed request."""
    return web.HTTPTooManyRequests(
        text=json.dumps(
            {
                "error": str(exc),
                "reason": "engine_overloaded",
                "retry_after_s": round(exc.retry_after_s, 2),
            }
        ),
        content_type="application/json",
        headers={"Retry-After": str(max(1, math.ceil(exc.retry_after_s)))},
    )


def _http_qos_shed(exc: QosShed) -> web.HTTPTooManyRequests:
    """429 for an admission refusal (qos/admission.py): same honest
    Retry-After contract as the engine shed, plus the machine-readable
    reason/tenant/class so a client (or operator) can see WHICH rule
    refused it — a rate-limited tenant backs off differently than a
    class under queue pressure."""
    return web.HTTPTooManyRequests(
        text=json.dumps(
            {
                "error": str(exc),
                "reason": exc.reason,
                "tenant": exc.tenant,
                "class": exc.qos_class,
                "retry_after_s": round(exc.retry_after_s, 2),
            }
        ),
        content_type="application/json",
        headers={"Retry-After": str(max(1, math.ceil(exc.retry_after_s)))},
    )


def _qos_admit(request: web.Request, engine) -> tuple:
    """Run QoS admission for a scoring request; returns the
    ``(tenant_label, qos_class)`` to stamp on the engine call. Raises
    the 429 itself on refusal. No controller / no QoS identity -> the
    defaults, zero extra work."""
    qos = request.get("qos")
    admission = request.app.get("qos_admission")
    if admission is None:
        return ("default", qos.qos_class if qos is not None else "interactive")
    if qos is None:
        from gordo_components_tpu.qos.classify import DEFAULT_REQUEST_CLASS

        qos = DEFAULT_REQUEST_CLASS
    depth = max_queue = 0
    drain_s = 0.05
    if engine is not None:
        max_queue = getattr(engine, "max_queue", 0)
        queue = getattr(engine, "_queue", None)
        depth = queue.qsize() if queue is not None else 0
        est = getattr(engine, "drain_estimate", None)
        if est is not None:
            drain_s = est(depth)
    try:
        label = admission.admit(
            qos, queue_depth=depth, max_queue=max_queue, drain_s=drain_s
        )
    except QosShed as exc:
        raise _http_qos_shed(exc)
    request["qos_label"] = label
    return (label, qos.qos_class)


def _note_deadline_expired_per_model(request: web.Request) -> None:
    """Observability for a per-model-path expiry (engine expiries count
    themselves): bump the engine's counter when one exists — a bank
    server's non-banked targets share the same
    ``gordo_engine_deadline_expired_total`` series the 504 runbook
    alerts on — and record the ``deadline_expired`` span."""
    engine = request.app.get("bank_engine")
    if engine is not None:
        engine.stats["deadline_expired"] += 1
    trace = request.get("trace")
    if trace is not None:
        now = time.monotonic()
        trace.add_span(
            "deadline_expired", now, now, error=True, where="per-model"
        )


def _http_deadline_exceeded(
    request: web.Request, exc: Optional[DeadlineExceeded] = None
) -> web.HTTPGatewayTimeout:
    """504 for a request whose time budget ran out before (or during)
    scoring. The body names the request id — the ONE request a client
    most wants to correlate is the one it already gave up on — and the
    middleware stamps the usual X-Request-Id/traceparent echo on the
    HTTPException headers, matching the 500/410 paths. Retrying an
    expired request verbatim is pointless (the same budget expires the
    same way), so unlike the 429 there is no Retry-After hint: raise
    the deadline or shed load instead."""
    rid = request.get("request_id")
    return web.HTTPGatewayTimeout(
        text=json.dumps(
            {
                "error": str(exc) if exc is not None else "deadline exceeded",
                "request_id": rid,
            }
        ),
        content_type="application/json",
    )


def _bank_coverage(request: web.Request, names) -> Any:
    """Operator-facing coverage: which models score through the HBM bank
    vs the per-model fallback path, and why (server/bank.py). None when
    the bank is disabled."""
    bank = request.app.get("bank")
    if bank is None:
        return None
    cov = bank.coverage()
    return {
        "banked": sorted(n for n in names if n in bank),
        "fallback": {
            n: cov["fallback"].get(n, "not bankable")
            for n in names
            if n not in bank
        },
        "n_buckets": cov["n_buckets"],
        "devices": cov["devices"],
    }


@routes.get("/gordo/v0/{project}/models")
async def list_models(request: web.Request) -> web.Response:
    body = {
        "project": request.match_info["project"],
        "models": _collection(request).names(),
        # advertised request encodings, in the server's preference order:
        # the bulk client upgrades its POST bodies to the best one it
        # also speaks (client/client.py). Tensor first — the framed
        # binary format (utils/wire.py) upgrades BOTH directions of the
        # wire and needs only numpy; parquet is deliberately demoted
        # below it (it only ever covered the request body, so it never
        # moved the bulk ratio — docs/architecture.md "Wire protocol")
        # and advertised only when a parse engine is importable, or
        # every advertised-then-posted body would 500.
        "accepts": ["application/json", TENSOR_CONTENT_TYPE]
        + (["application/x-parquet"] if _PARQUET_OK else []),
    }
    # local zero-copy transports (server/workers.py + utils/shm_ring.py):
    # the negotiation ladder a co-located client's transport="auto"
    # climbs — shm > uds > tcp, each rung verified locally before use
    transports = request.app.get("transports")
    if transports:
        body["transports"] = dict(transports)
    bank = _bank_coverage(request, body["models"])
    if bank is not None:
        body["bank"] = bank
    return web.json_response(body)


@routes.get("/gordo/v0/{project}/ready")
async def readiness(request: web.Request) -> web.Response:
    """O(1) readiness: the K8s probe fires every few seconds, and
    ``/models`` returns the full name list + per-model bank coverage —
    ~340 KB per probe at the 10k north star. This returns counts only;
    503 until the collection has loaded at least one model (matching
    the probe's previous effective gate on ``/models``)."""
    n = len(_collection(request).models)
    # a mesh replica with an EMPTY partition is ready: it owns nothing
    # right now (small fleet, or everything migrated away) but is a
    # healthy acquire target — 503ing it would get it restarted by the
    # probe exactly when the placement plane wants to hand it members
    ok = n > 0 or request.app.get("mesh") is not None
    body = {"ready": ok, "models": n}
    return web.json_response(body, status=200 if ok else 503)


def _healthz_body(app: web.Application) -> tuple:
    """Tri-state process health: ``ok`` | ``degraded`` | ``unhealthy``.

    ``degraded`` (still HTTP 200 — a liveness/readiness probe must NOT
    flap and restart a process that is serving its healthy majority)
    means a subset is impaired: models quarantined by the failure
    breaker, or artifacts the collection could not load on its latest
    scan. ``unhealthy`` (503) means nothing is servable. The body always
    says WHY, so "degraded" is a pager link, not a mystery."""
    collection = app.get("collection")
    quarantine = app.get("quarantine")
    bank = app.get("bank")
    models = len(collection.models) if collection is not None else 0
    load_failures = dict(collection.load_failures) if collection is not None else {}
    quarantined = quarantine.snapshot()["quarantined"] if quarantine is not None else {}
    finalize_failures = dict(getattr(bank, "finalize_failures", None) or {})
    if models == 0 and app.get("mesh") is None:
        status, http = "unhealthy", 503
    elif quarantined or load_failures or finalize_failures:
        status, http = "degraded", 200
    else:
        status, http = "ok", 200
    return {
        "status": status,
        "models": models,
        "quarantined": quarantined,
        "load_failures": load_failures,
        "bank_finalize_failures": finalize_failures,
    }, http


@routes.get("/healthz")
@routes.get("/gordo/v0/{project}/healthz")
async def healthz(request: web.Request) -> web.Response:
    body, status = _healthz_body(request.app)
    return web.json_response(body, status=status)


@routes.get("/gordo/v0/{project}/quarantine")
async def quarantine_list(request: web.Request) -> web.Response:
    quarantine = request.app.get("quarantine")
    if quarantine is None:
        return web.json_response({"enabled": False})
    return web.json_response({"enabled": True, **quarantine.snapshot()})


@routes.post("/gordo/v0/{project}/quarantine/clear")
async def quarantine_clear(request: web.Request) -> web.Response:
    """Operator action (see docs/operations.md runbook): re-admit
    quarantined models to routing. Body ``{"targets": [...]}`` clears the
    named models; an empty/absent body clears everything."""
    quarantine = request.app.get("quarantine")
    if quarantine is None:
        return web.json_response({"enabled": False, "cleared": []})
    targets = None
    if request.can_read_body:
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "expected a JSON body"}),
                content_type="application/json",
            )
        if body:
            targets = body.get("targets")
            if targets is not None and not isinstance(targets, list):
                raise web.HTTPBadRequest(
                    text=json.dumps({"error": "targets must be a list"}),
                    content_type="application/json",
                )
    cleared = quarantine.clear(targets)
    if cleared:
        _emit_event(request.app, "quarantine.clear", targets=cleared)
    return web.json_response({"enabled": True, "cleared": cleared})


@routes.get("/gordo/v0/{project}/metrics")
async def metrics_exposition(request: web.Request) -> web.Response:
    """Prometheus text-format exposition of the app's metrics registry
    (observability/): request counters/latency histograms, the batching
    engine's queue state, the bank router's per-shard routed/padded-row
    counters and per-bucket coalescing histograms, and live HBM gauges.
    The generated manifests annotate pods with this path for scraping;
    watchman scrapes it to build the fleet-wide rollup."""
    registry = request.app.get("metrics")
    if registry is None:
        raise web.HTTPNotFound(
            text=json.dumps({"error": "metrics registry not enabled"}),
            content_type="application/json",
        )
    return web.Response(
        body=registry.render().encode("utf-8"),
        headers={"Content-Type": "text/plain; version=0.0.4; charset=utf-8"},
    )


def _tracer_or_disabled(request: web.Request):
    tracer = request.app.get("tracer")
    if tracer is None or not tracer.enabled:
        return None
    return tracer


def _query_n(request: web.Request, default: str) -> Any:
    """``?n=`` as a non-negative int (0 = unbounded), else 400 — a
    negative value must not silently slice away the newest/slowest
    traces (``list[:-n]``), which are the ones the caller wants."""
    try:
        n = int(request.query.get("n", default))
    except ValueError:
        n = -1
    if n < 0:
        raise web.HTTPBadRequest(
            text=json.dumps({"error": "n must be a non-negative integer"}),
            content_type="application/json",
        )
    return n or None


def _traces_response(request: web.Request, traces) -> web.Response:
    """Shared tail for the trace endpoints: ``?format=chrome`` exports
    Chrome trace-event JSON (opens directly in chrome://tracing /
    Perfetto), the default is the summary+span-tree JSON."""
    if request.query.get("format") == "chrome":
        return web.json_response(chrome_trace(traces))
    return web.json_response(
        {"enabled": True, "traces": [t.summary() for t in traces]}
    )


@routes.get("/gordo/v0/{project}/traces")
async def traces_recent(request: web.Request) -> web.Response:
    """Recent retained traces (newest first), from the tracer's bounded
    ring. ``?id=<trace_id>`` retrieves one trace (ring + slow reservoir),
    ``?n=<count>`` bounds the list, ``?format=chrome`` exports the Trace
    Event Format. Sampling: head-sampled by ``GORDO_TRACE_SAMPLE``; a
    request carrying a ``traceparent`` with the sampled flag is always
    retained."""
    tracer = _tracer_or_disabled(request)
    if tracer is None:
        return web.json_response({"enabled": False, "traces": []})
    trace_id = request.query.get("id")
    if trace_id:
        return _traces_response(request, tracer.find(trace_id))
    return _traces_response(
        request, tracer.recent(_query_n(request, default="50"))
    )


@routes.get("/gordo/v0/{project}/traces/slow")
async def traces_slow(request: web.Request) -> web.Response:
    """The slow-request flight recorder: worst-N traces by duration,
    slowest first — retained regardless of head sampling, so the tail is
    always explorable. Same ``?n=``/``?format=chrome`` options."""
    tracer = _tracer_or_disabled(request)
    if tracer is None:
        return web.json_response({"enabled": False, "traces": []})
    return _traces_response(
        request, tracer.slow(_query_n(request, default="0"))
    )


@routes.get("/gordo/v0/{project}/slo")
async def slo_view(request: web.Request) -> web.Response:
    """Rolling multi-window SLO state (observability/slo.py): per
    configured objective (availability / p99 latency / goodput ratio),
    the windowed good/total deltas, ratios, and burn rates over the
    5m/1h/6h windows, plus the worst burn across all of them.

    The body is the SAME cached snapshot the registry's
    ``gordo_slo_burn_rate`` gauges render and ``/stats`` embeds (the
    no-drift contract — byte-identical between samples). ``?refresh=1``
    forces a fresh sample first (operator / test hook; the background
    cadence is ``GORDO_SLO_SAMPLE_S``). Watchman's ``GET /slo`` merges
    this body fleet-wide."""
    tracker = request.app.get("slo")
    if tracker is None:
        return web.json_response({"enabled": False})
    if request.query.get("refresh", "").lower() in ("1", "true", "yes"):
        tracker.sample(force=True)
    body = {"enabled": True, **tracker.snapshot()}
    ledger = request.app.get("goodput")
    if ledger is not None:
        body["goodput"] = ledger.snapshot()
    return web.json_response(body)


@routes.get("/gordo/v0/{project}/qos")
async def qos_view(request: web.Request) -> web.Response:
    """Multi-tenant QoS state (qos/): the admission controller's tenant
    buckets / per-class shed thresholds / admitted+shed counters, and
    the engine's weighted-fair queue (class weights, per-class depth,
    virtual clocks, dequeue counts) plus per-class engine counters —
    the page an operator reads during overload triage to answer "which
    class is shedding, and why" (docs/operations.md runbook). Counters
    are the SAME dicts the registry renders (no-drift)."""
    admission = request.app.get("qos_admission")
    body: Dict[str, Any] = {
        "enabled": admission is not None,
        "admission": admission.snapshot() if admission is not None else {},
    }
    engine = request.app.get("bank_engine")
    if engine is not None and hasattr(engine, "qos_snapshot"):
        body["engine"] = engine.qos_snapshot()
    return web.json_response(body)


@routes.get("/gordo/v0/{project}/heat")
async def heat_view(request: web.Request) -> web.Response:
    """Per-member access heat (observability/heat.py): the decayed
    routed-row rate accountant's tier counts, per-bucket breakdown, and
    rate histogram, plus the ``?top=N`` hottest/coldest member rankings
    (default 10 — the ONLY per-member surface; the registry exports
    bounded tier/histogram series, never per-member ones).

    The body is the SAME cached snapshot the registry's
    ``gordo_heat_*`` series render and ``/stats`` embeds (no-drift);
    ``?refresh=1`` forces a fold first (operator/test hook — the normal
    cadence is ``GORDO_HEAT_SAMPLE_S``). Watchman's ``GET /heat`` sums
    these bodies into one fleet-ranked list."""
    heat = request.app.get("heat")
    if heat is None:
        return web.json_response({"enabled": False})
    if request.query.get("refresh", "").lower() in ("1", "true", "yes"):
        heat.sample(force=True)
    body = {"enabled": True, **heat.snapshot()}
    top = _query_float(request, "top")
    body.update(heat.ranked(10 if top is None else int(top)))
    return web.json_response(body)


@routes.get("/gordo/v0/{project}/costs")
async def costs_view(request: web.Request) -> web.Response:
    """Per-bucket device-cost attribution (observability/cost.py):
    analytic FLOPs/row × the goodput ledger's measured device seconds
    and real-vs-padded row split, per bucket — MFU, device-seconds-per-
    1k-rows, pad-waste score — plus the ``ranking`` list ordering
    buckets by wasted device time (pad waste × device share).

    The body is the SAME cached join the registry's ``gordo_bucket_*``
    cost series render and ``/stats`` embeds (no-drift); ``?refresh=1``
    forces a fresh join. Watchman's ``GET /costs`` sums the raw tallies
    fleet-wide and recomputes through the same arithmetic."""
    cost = request.app.get("cost")
    if cost is None:
        return web.json_response({"enabled": False})
    if request.query.get("refresh", "").lower() in ("1", "true", "yes"):
        cost.sample(force=True)
    return web.json_response({"enabled": True, **cost.snapshot()})


def _query_float(request: web.Request, name: str) -> Optional[float]:
    raw = request.query.get(name)
    if raw in (None, ""):
        return None
    try:
        return float(raw)
    except ValueError:
        raise web.HTTPBadRequest(
            text=json.dumps({"error": f"{name} must be a number, got {raw!r}"}),
            content_type="application/json",
        )


@routes.get("/gordo/v0/{project}/history")
async def history_view(request: web.Request) -> web.Response:
    """Retained metric history (observability/timeseries.py): the
    flight recorder's time axis. Without ``?series=``, the store meta +
    retained series names; with ``?series=a,b`` (plus optional
    ``since``/``until`` epoch seconds and ``step`` seconds), the points
    from the finest tier that covers the range. Disabled
    (``GORDO_HISTORY`` unset) answers ``{"enabled": false}`` — the
    watchman rollup counts such replicas out instead of erroring."""
    store = request.app.get("history")
    if store is None:
        return web.json_response({"enabled": False})
    body: Dict[str, Any] = store.snapshot()
    series_raw = request.query.get("series", "")
    names = [s for s in series_raw.split(",") if s]
    if names:
        body["series"] = store.query(
            names,
            since=_query_float(request, "since"),
            until=_query_float(request, "until"),
            step=_query_float(request, "step"),
        )
    else:
        body["names"] = store.series_names()
    return web.json_response(body)


@routes.get("/gordo/v0/{project}/events")
async def events_view(request: web.Request) -> web.Response:
    """Structured event timeline (observability/events.py): every state
    transition this replica performed — swaps, reloads, quarantine
    flips, mesh moves, canary/fault activity — oldest-first. Filters:
    ``?since=<seq>`` (resume a tail), ``?since_wall=<epoch s>``,
    ``?type=a,b`` (comma-separated), ``?limit=n`` (newest n)."""
    events = request.app.get("events")
    if events is None:
        return web.json_response({"enabled": False, "events": []})
    types_raw = request.query.get("type", "")
    types = [t for t in types_raw.split(",") if t] or None
    since_seq = _query_float(request, "since") or 0
    limit = _query_float(request, "limit")
    body = {"enabled": True, **events.snapshot()}
    body["events"] = events.events(
        since_seq=int(since_seq),
        types=types,
        since_wall=_query_float(request, "since_wall"),
        limit=None if limit is None else int(limit),
    )
    return web.json_response(body)


@routes.get("/gordo/v0/{project}/stats")
async def server_stats(request: web.Request) -> web.Response:
    """Serving-process observability (SURVEY.md §5 metrics): request
    counters by endpoint kind, error count, uptime, and the continuous
    -batching engine's coalescing effectiveness (avg rolled-up batch
    size is THE number that explains bank throughput)."""
    stats = request.app.get("stats") or {}
    body: Any = {
        "uptime_seconds": round(
            time.time() - stats.get("started_at", time.time()), 1
        ),
        "requests": dict(stats.get("requests", {})),
        "errors": int(stats.get("errors", 0)),
        "models": len(_collection(request).models),
        # per-endpoint-kind service time percentiles (SLO evidence: the
        # tail under coalescing, not just throughput — VERDICT r3 #4)
        "latency": {
            kind: hist.snapshot()
            for kind, hist in stats.get("latency", {}).items()
        },
        # exemplar-style links from latency buckets to traces: per
        # endpoint kind, the last trace id to land in each histogram
        # bucket (keyed by the bucket's le edge) — paste the trace_id
        # into GET .../traces?id=... to see where that request's time
        # went (metric spike -> offending trace in two clicks)
        "exemplars": stats.get("exemplars", {}),
        # the data plane by encoding (json|parquet|tensor): scoring and
        # ingest POST counts + request body bytes — the same cells the
        # gordo_server_request{,_bytes}_total{encoding} series render
        "wire": {
            "requests": dict(stats.get("wire", {}).get("requests", {})),
            "bytes": dict(stats.get("wire", {}).get("bytes", {})),
        },
        # multi-worker accept balance (server/workers.py): requests
        # parsed per worker loop — empty outside pool mode
        "workers": dict(stats.get("workers", {})),
    }
    shm = stats.get("shm")
    if shm is not None:
        # the shared-memory ring's data plane (utils/shm_ring.py)
        body["shm"] = dict(shm)
    transports = request.app.get("transports")
    if transports:
        body["transports"] = dict(transports)
    engine = request.app.get("bank_engine")
    if engine is not None:
        es = dict(engine.stats)
        if es.get("batches"):
            es["avg_batch"] = round(es["requests"] / es["batches"], 2)
        # the flush_ms trade, quantified: how long requests sat waiting
        # for their batch vs total submit->result service time
        es["queue_wait"] = engine.queue_wait.snapshot()
        es["service"] = engine.service.snapshot()
        # backpressure visibility: bound, live depth, and sheds (the
        # "shed" counter rides in from engine.stats)
        es["max_queue"] = engine.max_queue
        es["queue_depth"] = engine._queue.qsize()
        # per-class attribution (ISSUE 19): requests/sheds/expiries by
        # priority class, the same dicts /metrics renders
        if getattr(engine, "class_stats", None):
            es["by_class"] = {
                c: dict(cs) for c, cs in engine.class_stats.items()
            }
        body["bank_engine"] = es
    worker_engines = request.app.get("worker_engines")
    if worker_engines:
        # the per-worker-loop engines of the multi-worker pool: their
        # coalescing/shed state, next to the primary engine's above
        body["worker_engines"] = {
            wid: {
                **dict(weng.stats),
                "queue_depth": weng._queue.qsize(),
            }
            for wid, weng in worker_engines
        }
    bank = request.app.get("bank")
    if bank is not None:
        body["bank_models"] = len(bank)
        pipeline = getattr(bank, "pipeline_stats", None)
        if pipeline is not None:
            # the scoring pipeline's health at a glance: in-flight
            # window, padded-buffer arena hit rate, and the measured
            # host/device overlap ratio across multi-group calls
            body["bank_pipeline"] = pipeline()
        capacity = getattr(bank, "capacity_stats", None)
        if capacity is not None:
            # the HBM capacity picture: storage dtype, weight bytes per
            # member, models-per-GB, and any buckets whose quantization
            # fell back to fp32 (docs/observability.md contract)
            body["bank_capacity"] = capacity()
    quarantine = request.app.get("quarantine")
    if quarantine is not None:
        # the degraded-mode surface: which models the breaker evicted
        # (and why), plus the pre-quarantine failure streaks in flight
        body["quarantine"] = quarantine.snapshot()
    ledger = request.app.get("goodput")
    if ledger is not None:
        # the goodput ledger: wall/device time by class (goodput vs
        # wasted vs padded), host-stage overhead, per-bucket/per-shard
        # breakdowns — the same cells /metrics renders
        body["goodput"] = ledger.snapshot()
    tracker = request.app.get("slo")
    if tracker is not None:
        # the SLO state GET .../slo serves, embedded verbatim (no-drift)
        body["slo"] = tracker.snapshot()
    heat = request.app.get("heat")
    if heat is not None:
        # the access-heat tiers GET .../heat serves, embedded verbatim
        # (no-drift; the per-member rankings stay on /heat?top=)
        body["heat"] = heat.snapshot()
    cost = request.app.get("cost")
    if cost is not None:
        # the per-bucket MFU/cost join GET .../costs serves (no-drift)
        body["costs"] = cost.snapshot()
    collection = request.app.get("collection")
    if collection is not None:
        body["load_failures"] = {
            "current": dict(collection.load_failures),
            "total": collection.load_failed_total,
        }
    registry = request.app.get("metrics")
    if registry is not None:
        # the registry's JSON view: the SAME cells /metrics renders (per-
        # shard routed/padded counters, engine shed/queue-depth, ...), so
        # the human-readable endpoint and the scrape endpoint cannot drift
        body["metrics"] = registry.snapshot()
    return web.json_response(body)


@routes.get("/gordo/v0/{project}/metadata-all")
async def metadata_all(request: web.Request) -> web.Response:
    """Every target's health + metadata in ONE response.

    The reference's watchman had to poll one pod per model; against a
    collection server that per-target pattern costs O(2N) HTTP requests
    per snapshot (20k requests/30s at the 10k-model north star) hammering
    the same process that serves scoring traffic. A model present in the
    collection is loaded and servable, so ``healthy`` mirrors what
    per-target ``/healthcheck`` (200 iff present) would report.

    ``?digest=1``: per-target health + a bounded metadata digest
    (utils/digest.py) instead of full metadata — O(1) requests AND
    O(small) bytes for watchman's periodic polling; full metadata stays
    available without the flag and per-target."""
    from gordo_components_tpu.utils.digest import metadata_digest

    want_digest = (
        request.query.get("digest", "").lower() in ("1", "true", "yes")
    )
    # ONE consistent (models, metadata) state: a concurrent /reload swaps
    # the collection atomically, so reading both sides from one snapshot
    # can neither 500 nor drop a target mid-reload
    models, metadata = _collection(request).snapshot()
    names = sorted(models)
    targets = {}
    for name in names:
        entry = {"healthy": True}
        meta = metadata.get(name)
        if meta is not None:
            if want_digest:
                entry["digest"] = metadata_digest(meta)
            else:
                entry["endpoint-metadata"] = meta
        targets[name] = entry
    body = {"project": request.match_info["project"], "targets": targets}
    bank = _bank_coverage(request, names)
    if bank is not None:
        body["bank"] = bank
    resp = web.json_response(body)
    if want_digest:
        # digest bodies are highly repetitive JSON (same keys per target);
        # gzip takes a 10k-fleet snapshot from a few MB to a few hundred
        # KB on the wire. DELIBERATELY digest-only: aiohttp compresses
        # synchronously on the event loop, and gzipping a tens-of-MB full
        # body would stall every concurrent scoring request — full-body
        # consumers (the bulk client) are rare and throughput-bound, not
        # wire-bound
        resp.enable_compression()
    return resp


async def _swap_collection_bank(app: web.Application, loop) -> tuple:
    """Rebuild the HBM bank from the collection's CURRENT models and land
    it through the zero-downtime swap primitive (placement/swap.py): the
    replacement builds + warm-compiles off to the side (same mesh/
    registry/pipeline/precision config and goodput ledger the app booted
    with, so counters stay monotonic and tuning never silently resets),
    then one generation flip moves serving over — in-flight batches
    drain on the old bank, so there is no 5xx window. Shared by /reload
    and the mesh acquire/release endpoints (one swap discipline, not
    three). Caller MUST hold the reload lock. Returns
    ``(bank_models, swap_info)`` — ``(None, None)`` when the bank is
    disabled."""
    if not app.get("bank_enabled"):
        return None, None
    from gordo_components_tpu.placement.swap import (
        _restore_collectors,
        build_bank,
        snapshot_collectors,
        swap_bank,
    )

    collection = app["collection"]
    prev_collectors = snapshot_collectors(app.get("metrics"))
    try:
        bank = await loop.run_in_executor(
            None, functools.partial(build_bank, app, collection.models)
        )
    except Exception:
        # a stillborn build must not leave the registry pointing at its
        # dead collectors — the serving bank's series keep rendering
        # (swap_bank handles the flip-failure case itself)
        _restore_collectors(app.get("metrics"), prev_collectors)
        raise
    result = swap_bank(app, bank, prev_collectors=prev_collectors)
    controller = app.get("placement")
    if controller is not None:
        # every swap path shares the controller's stats/pause histogram:
        # the generation GET /placement reports must agree with whoever
        # bumped it (reload, rebalance, or a mesh ownership change)
        controller.record_swap(result)
    return result.bank_models, {
        "generation": result.generation,
        "pause_ms": round(result.pause_s * 1e3, 3),
    }


@routes.post("/gordo/v0/{project}/reload")
async def reload_models(request: web.Request) -> web.Response:
    """Rescan the artifact dir and serve new/updated models without a
    restart: the builder writes artifacts, then POSTs here (the reference
    rolled a new pod per model instead). Rebuilds the HBM bank when
    enabled.

    Serialized with an app-level lock: concurrent reloads would otherwise
    run ``collection.refresh()`` on separate executor threads (mutating
    models/metadata under readers) and each would rebuild the full HBM
    bank — making repeated POSTs a cheap DoS on device memory/compute."""
    app = request.app
    lock = get_reload_lock(app)
    collection = _collection(request)
    loop = asyncio.get_running_loop()
    async with lock:
        changes = await loop.run_in_executor(None, collection.refresh)
        quarantine = app.get("quarantine")
        if quarantine is not None:
            # a replaced or removed artifact gets a clean slate: the
            # quarantine verdict belonged to the OLD bytes
            for name in changes["updated"] + changes["removed"]:
                quarantine.drop(name)
        bank_models, swap_info = await _swap_collection_bank(app, loop)
    _emit_event(
        app,
        "models.reload",
        added=len(changes.get("added", ())),
        updated=len(changes.get("updated", ())),
        removed=len(changes.get("removed", ())),
    )
    body = {
        "changes": changes,
        "models": collection.names(),
        "bank_models": bank_models,
    }
    if swap_info is not None:
        body["swap"] = swap_info
    return web.json_response(body)


@routes.get("/gordo/v0/{project}/placement")
async def placement_view(request: web.Request) -> web.Response:
    """The live model->shard placement (placement control plane): per
    bucket, the members in stack order with their per-shard observed
    window loads, the current bank generation, the controller's knobs
    and counters, and — with ``?dry_run=1`` — a full plan preview
    (what ``POST /rebalance`` would do right now, without doing it)."""
    controller = request.app.get("placement")
    if controller is None:
        return web.json_response({"enabled": False})
    dry_run = request.query.get("dry_run", "").lower() in ("1", "true", "yes")
    return web.json_response(controller.placement_view(dry_run=dry_run))


@routes.post("/gordo/v0/{project}/rebalance")
async def rebalance(request: web.Request) -> web.Response:
    """Evaluate the rebalance planner and apply the plan via the
    zero-downtime swap. Body (optional JSON): ``{"force": true}``
    applies a skew-reducing plan even below the improvement threshold
    (operator override). ``?dry_run=1`` evaluates without applying.
    A failed swap rolls back to the old generation (the old bank keeps
    serving every request) and answers 500 with ``rolled_back``."""
    controller = request.app.get("placement")
    if controller is None:
        raise web.HTTPNotFound(
            text=json.dumps({"error": "placement control plane not enabled"}),
            content_type="application/json",
        )
    force = False
    if request.can_read_body:
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "expected a JSON body"}),
                content_type="application/json",
            )
        if isinstance(body, dict):
            force = bool(body.get("force", False))
        elif body:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "expected a JSON object body"}),
                content_type="application/json",
            )
    dry_run = request.query.get("dry_run", "").lower() in ("1", "true", "yes")
    try:
        result = await controller.rebalance(force=force, dry_run=dry_run)
    except Exception as exc:
        # swap_bank's rollback contract already ran: the old generation
        # is serving, nothing was dropped — the 500 reports the failed
        # ATTEMPT, not a degraded server
        logger.exception("rebalance failed (rolled back)")
        return web.json_response(
            {
                "error": f"{type(exc).__name__}: {exc}",
                "rolled_back": True,
                "generation": int(request.app.get("bank_generation", 0)),
                "request_id": request.get("request_id"),
            },
            status=500,
        )
    if not dry_run:
        _emit_event(
            request.app,
            "rebalance.applied" if result.get("applied") else "rebalance.plan",
            moves=len((result.get("plan") or {}).get("moves") or ()),
            applied=bool(result.get("applied")),
        )
    return web.json_response(result)


# ---------------------------------------------------------------------- #
# multi-host serving mesh (parallel/distributed.py + watchman routing):
# ownership introspection, artifact shipping, and the acquire/release
# halves of a cross-replica member migration. Every ownership change
# lands through the SAME zero-downtime swap /reload uses, so a migration
# has no 5xx window on either side.
# ---------------------------------------------------------------------- #


@routes.get("/gordo/v0/{project}/mesh")
async def mesh_view(request: web.Request) -> web.Response:
    """This replica's mesh identity + live ownership: which members it
    serves right now (the boot partition plus/minus any acquire/release
    since). Watchman's routing table is built from exactly this truth
    (via ``/models`` — same collection), so the view exists for
    operators and tests to see the partition without joining metrics."""
    identity = request.app.get("mesh")
    collection = _collection(request)
    body: Any = {
        "enabled": identity is not None,
        "owned": collection.names(),
        "generation": int(request.app.get("bank_generation", 0)),
    }
    if identity is not None:
        body.update(
            {
                "replica_id": identity.replica_id,
                "replica_count": identity.replica_count,
                "distributed": identity.distributed,
                "coordinator": identity.coordinator,
            }
        )
    return web.json_response(body)


def _member_artifact_dir(request: web.Request, target: str) -> str:
    """The on-disk artifact dir for an OWNED member, or 404 with the
    reason (never a bare 404: a migration driver must be able to tell
    "wrong replica" from "typo'd member")."""
    from gordo_components_tpu.server.model_io import scan_artifacts

    collection = _collection(request)
    if target not in collection:
        raise web.HTTPNotFound(
            text=json.dumps(
                {
                    "error": f"member {target!r} is not owned by this replica",
                    "owned": len(collection.models),
                }
            ),
            content_type="application/json",
        )
    path = scan_artifacts(collection.root, collection.target_name).get(target)
    if path is None:  # owned in memory but artifact vanished from disk
        raise web.HTTPNotFound(
            text=json.dumps(
                {"error": f"member {target!r} has no artifact dir on disk"}
            ),
            content_type="application/json",
        )
    return path


@routes.get("/gordo/v0/{project}/mesh/member/{target}/artifact")
async def mesh_member_artifact(request: web.Request) -> web.Response:
    """The member's artifact dir as a gzipped tar — the shipping half of
    a cross-replica migration (the acquiring replica pulls this, lands
    it under its own root, then loads + swaps). Packed on an executor
    thread: tar+gzip of a model artifact must not stall the event loop
    that is serving scoring traffic."""
    target = request.match_info["target"]
    path = _member_artifact_dir(request, target)
    from gordo_components_tpu.server.model_io import pack_artifact_dir

    data = await asyncio.get_running_loop().run_in_executor(
        None, pack_artifact_dir, path
    )
    return web.Response(
        body=data,
        content_type="application/gzip",
        headers={"X-Gordo-Member": target},
    )


async def _mesh_body(request: web.Request) -> dict:
    """The JSON object body every mesh mutation takes (400 otherwise).

    The member name is validated as a bare directory name: acquire joins
    it into the artifact root and unpacks a network-supplied archive
    there, so separators, ``..``, or an absolute path would let a
    hostile caller aim the write outside the root entirely (the archive
    guards in ``unpack_artifact_dir`` protect paths INSIDE the archive,
    not the destination)."""
    try:
        body = await request.json()
    except Exception:
        body = None
    member = (body or {}).get("member") if isinstance(body, dict) else None
    if (
        not isinstance(member, str)
        or not member
        or member != os.path.basename(member)
        or member in (".", "..")
        or os.path.isabs(member)
    ):
        raise web.HTTPBadRequest(
            text=json.dumps(
                {
                    "error": 'expected a JSON body {"member": "<name>", ...} '
                             "with a plain member name (no path separators)"
                }
            ),
            content_type="application/json",
        )
    return body


@routes.post("/gordo/v0/{project}/mesh/acquire")
async def mesh_acquire(request: web.Request) -> web.Response:
    """Take ownership of a member. Body: ``{"member": name}`` (artifact
    already on this replica's disk — the shared-volume deploy, and the
    replica-loss recovery path) or ``{"member": name, "source": url}``
    (pull the artifact from the source replica's ``.../artifact``
    endpoint first — the cross-host shipping path).

    Ordering contract (watchman's migration sequence): acquire runs
    BEFORE the source's release, so mid-migration the member is owned by
    BOTH replicas and either answers — the zero-non-200 window. The new
    bank generation lands through the same zero-downtime swap as
    ``/reload``. Idempotent: acquiring an already-owned member is a
    no-op 200 (a retried migration step must not rebuild the bank)."""
    app = request.app
    body = await _mesh_body(request)
    member = body["member"]
    source = body.get("source")
    if source is not None and not isinstance(source, str):
        raise web.HTTPBadRequest(
            text=json.dumps({"error": "source must be a URL string"}),
            content_type="application/json",
        )
    collection = _collection(request)
    loop = asyncio.get_running_loop()
    lock = get_reload_lock(app)
    async with lock:
        if member in collection:
            return web.json_response(
                {
                    "acquired": False,
                    "already_owned": True,
                    "member": member,
                    "generation": int(app.get("bank_generation", 0)),
                }
            )
        if source:
            # pull the artifact from the losing replica (bounded: a hung
            # source must not pin this replica's reload lock forever)
            import aiohttp as _aiohttp

            from gordo_components_tpu.resilience.deadline import Deadline
            from gordo_components_tpu.server.model_io import unpack_artifact_dir

            url = (
                f"{source.rstrip('/')}/gordo/v0/"
                f"{request.match_info['project']}/mesh/member/{member}/artifact"
            )

            async def fetch():
                async with _aiohttp.ClientSession() as session:
                    async with session.get(url) as resp:
                        if resp.status != 200:
                            raise ValueError(
                                f"source replied {resp.status}: "
                                f"{(await resp.text())[:300]}"
                            )
                        return await resp.read()

            try:
                raw = await Deadline(120.0).wait_for(fetch())
                await loop.run_in_executor(
                    None,
                    unpack_artifact_dir,
                    raw,
                    os.path.join(collection.root, member),
                )
            except Exception as exc:
                return web.json_response(
                    {
                        "acquired": False,
                        "member": member,
                        "error": f"artifact fetch from {source} failed: "
                                 f"{type(exc).__name__}: {exc}",
                    },
                    status=502,
                )
        try:
            changes = await loop.run_in_executor(
                None, collection.acquire, member
            )
        except FileNotFoundError as exc:
            raise web.HTTPNotFound(
                text=json.dumps(
                    {
                        "error": str(exc),
                        "hint": 'pass {"source": "<replica base url>"} to '
                                "ship the artifact first",
                    }
                ),
                content_type="application/json",
            )
        quarantine = app.get("quarantine")
        if quarantine is not None:
            # freshly shipped bytes get a clean breaker slate
            quarantine.drop(member)
        try:
            bank_models, swap_info = await _swap_collection_bank(app, loop)
        except Exception as exc:
            # roll ownership back: serving a member the bank rebuild
            # rejected would route its traffic into per-model fallbacks
            # nobody planned for — the old generation keeps serving and
            # the migration driver sees a clean failure to retry
            await loop.run_in_executor(None, collection.release, member)
            logger.exception("mesh acquire of %r failed at bank swap", member)
            return web.json_response(
                {
                    "acquired": False,
                    "member": member,
                    "rolled_back": True,
                    "error": f"{type(exc).__name__}: {exc}",
                    "generation": int(app.get("bank_generation", 0)),
                },
                status=500,
            )
    _emit_event(
        app, "mesh.acquire", member=member, shipped=bool(source)
    )
    return web.json_response(
        {
            "acquired": True,
            "member": member,
            "shipped": bool(source),
            "changes": changes,
            "bank_models": bank_models,
            "swap": swap_info,
            "owned": collection.names(),
        }
    )


@routes.post("/gordo/v0/{project}/mesh/release")
async def mesh_release(request: web.Request) -> web.Response:
    """Drop ownership of a member (the source's half of a migration,
    AFTER the target acquired and the routing table moved). The artifact
    stays on disk — a failed migration re-acquires locally instead of
    re-shipping — and the new (smaller) bank generation lands through
    the zero-downtime swap. 404 with the reason for a member this
    replica does not own."""
    app = request.app
    body = await _mesh_body(request)
    member = body["member"]
    collection = _collection(request)
    loop = asyncio.get_running_loop()
    lock = get_reload_lock(app)
    async with lock:
        try:
            changes = await loop.run_in_executor(
                None, collection.release, member
            )
        except KeyError as exc:
            raise web.HTTPNotFound(
                text=json.dumps({"error": str(exc.args[0])}),
                content_type="application/json",
            )
        quarantine = app.get("quarantine")
        if quarantine is not None:
            quarantine.drop(member)
        try:
            bank_models, swap_info = await _swap_collection_bank(app, loop)
        except Exception as exc:
            # re-acquire locally (the artifact is still on disk): a
            # failed rebuild must not leave the member unowned ANYWHERE
            # while the routing table still points here. Off the event
            # loop (it re-loads the artifact), and guarded: if the
            # re-acquire ALSO fails (artifact corrupt — likely the same
            # root cause) the 500 must still answer, flagged so the
            # migration driver knows the member truly has no owner here
            reacquired = True
            try:
                await loop.run_in_executor(None, collection.acquire, member)
            except Exception:
                reacquired = False
                logger.exception(
                    "mesh release rollback could not re-acquire %r; the "
                    "member is NOT served by this replica", member,
                )
            logger.exception("mesh release of %r failed at bank swap", member)
            return web.json_response(
                {
                    "released": False,
                    "member": member,
                    "rolled_back": reacquired,
                    "reacquire_failed": not reacquired,
                    "error": f"{type(exc).__name__}: {exc}",
                    "generation": int(app.get("bank_generation", 0)),
                },
                status=500,
            )
    _emit_event(app, "mesh.release", member=member)
    return web.json_response(
        {
            "released": True,
            "member": member,
            "changes": changes,
            "bank_models": bank_models,
            "swap": swap_info,
            "owned": collection.names(),
        }
    )


def _stream_plane(request: web.Request):
    """The streaming adaptation plane, or a 404 naming the knob — a
    plain 404 would read as a typo'd URL, not a disabled feature."""
    plane = request.app.get("stream")
    if plane is None:
        raise web.HTTPNotFound(
            text=json.dumps(
                {"error": "streaming plane not enabled (GORDO_STREAM=0)"}
            ),
            content_type="application/json",
        )
    return plane


@routes.get("/gordo/v0/{project}/drift")
async def drift_view(request: web.Request) -> web.Response:
    """Per-member drift state over the streaming window buffers
    (streaming/drift.py): EWMA reconstruction-error drift vs the
    train-time thresholds, input out-of-training-range fraction,
    watermark lag and staleness, plus the currently drifted member list.
    ``?refresh=1`` runs a fresh evaluation sweep first (device work, off
    the event loop); the default serves the last sweep's state."""
    plane = request.app.get("stream")
    if plane is None:
        return web.json_response({"enabled": False})
    if request.query.get("refresh", "").lower() in ("1", "true", "yes"):
        await plane.evaluate()
    return web.json_response({"enabled": True, **plane.drift_view()})


@routes.post("/gordo/v0/{project}/{target}/ingest")
async def ingest_rows(request: web.Request) -> web.Response:
    """Streaming ingestion: append fresh rows to the target's window
    buffer. Body: ``{"rows": [[...], ...], "timestamps": [...]}`` —
    timestamps are epoch seconds or ISO-8601 strings (optional: absent
    means "arrived now"); ``null`` cells mark sensor dropout. Late rows
    (behind the watermark by more than ``GORDO_STREAM_LATENESS_S``) are
    counted and dropped, out-of-order rows within the allowance are
    accepted — the response reports both.

    Binary bodies (``application/x-gordo-tensor``, the scoring plane's
    frame format) carry a float32 ``rows`` frame (NaN cells = dropout —
    the wire needs no null boxing) and an optional float64 epoch-seconds
    ``timestamps`` frame; live windows stream at the same zero-copy cost
    as scoring."""
    plane = _stream_plane(request)
    _get_model(request)  # 404 for unknown targets, same as scoring
    target = request.match_info["target"]
    if _request_encoding(request) == "tensor":
        raw = await request.read()
        try:
            frames = unpack_frames(raw)
            if "rows" not in frames:
                raise WireFormatError(
                    f"tensor ingest body must carry a 'rows' frame "
                    f"(got {sorted(frames)})"
                )
            values = rows_as_f32(frames["rows"], "rows")
            ts = frames.get("timestamps")
            if ts is None:
                # "arrived now" on the plane's clock seam: under replay
                # this is the replayed now, not the compressing wall
                event_ts = np.full((len(values),), plane.clock.time())
            else:
                event_ts = np.asarray(ts, np.float64).reshape(-1)
                if len(event_ts) != len(values):
                    raise WireFormatError(
                        f"{len(event_ts)} timestamps for {len(values)} rows"
                    )
            counts = plane.ingest(target, event_ts, values)
        except (WireFormatError, ValueError) as exc:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": str(exc)}),
                content_type="application/json",
            )
        return web.json_response({"target": target, **counts})
    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(
            text=json.dumps({"error": "expected a JSON body with rows"}),
            content_type="application/json",
        )
    rows = body.get("rows") if isinstance(body, dict) else None
    if not isinstance(rows, list) or not rows:
        raise web.HTTPBadRequest(
            text=json.dumps({"error": "rows must be a non-empty list of lists"}),
            content_type="application/json",
        )
    try:
        values = np.asarray(
            [[np.nan if v is None else v for v in r] for r in rows],
            dtype=np.float32,
        )
        raw_ts = body.get("timestamps")
        if raw_ts is None:
            event_ts = np.full((len(values),), plane.clock.time())
        elif not isinstance(raw_ts, list):
            raise ValueError("timestamps must be a list")
        elif len(raw_ts) != len(values):
            raise ValueError(
                f"{len(raw_ts)} timestamps for {len(values)} rows"
            )
        elif raw_ts and isinstance(raw_ts[0], str):
            # asi8 is in the index's own unit (ns/us/ms/s in pandas 2.x
            # — see dataset/resample.py); normalize to ns first
            event_ts = (
                pd.to_datetime(raw_ts, utc=True).as_unit("ns").asi8 / 1e9
            )
        else:
            event_ts = np.asarray(raw_ts, np.float64)
        counts = plane.ingest(target, event_ts, values)
    except ValueError as exc:
        raise web.HTTPBadRequest(
            text=json.dumps({"error": str(exc)}), content_type="application/json"
        )
    return web.json_response({"target": target, **counts})


@routes.get("/gordo/v0/{project}/{target}/results/stream")
async def results_stream(request: web.Request) -> web.Response:
    """Push-mode long poll (streaming/push.py): scored-window results
    for the target since the subscriber's last poll, waiting up to
    ``?timeout=`` (default 10s, max 60) for the first one. Pass a stable
    ``?subscriber=`` id to keep one bounded queue across polls (absent:
    a fresh id is minted and echoed — results published BEFORE the
    first poll with it are not replayed). The response's ``dropped``
    counts results this subscriber lost to its bounded queue
    (drop-oldest — the backpressure rule); 429 past
    ``GORDO_PUSH_SUBSCRIBERS_MAX`` subscribers."""
    plane = _stream_plane(request)
    broker = getattr(plane, "broker", None)
    if broker is None:
        raise web.HTTPNotFound(
            text=json.dumps(
                {"error": "push mode not enabled (GORDO_PUSH=0)"}
            ),
            content_type="application/json",
        )
    _get_model(request)  # unknown targets 404, same as scoring
    target = request.match_info["target"]
    subscriber = request.query.get("subscriber", "")[:128]
    if not subscriber:
        import uuid

        subscriber = uuid.uuid4().hex[:12]
    try:
        timeout = float(request.query.get("timeout", "10"))
    except ValueError:
        raise web.HTTPBadRequest(
            text=json.dumps({"error": "timeout must be a number"}),
            content_type="application/json",
        )
    timeout = min(max(timeout, 0.0), 60.0)
    if not broker.subscribe(subscriber, target):
        # consistent shed contract (ISSUE 19 satellite): every 429 in
        # the serving plane carries Retry-After + a machine-readable
        # retry_after_s. A full subscriber table drains on the poll
        # timeout cadence — a vacated slot appears within one long-poll
        # window, so that IS the honest retry hint.
        retry_s = max(timeout, 1.0)
        raise web.HTTPTooManyRequests(
            text=json.dumps(
                {
                    "error": "push subscriber table full "
                    "(GORDO_PUSH_SUBSCRIBERS_MAX)",
                    "reason": "push_subscribers_full",
                    "retry_after_s": round(retry_s, 2),
                }
            ),
            content_type="application/json",
            headers={"Retry-After": str(max(1, math.ceil(retry_s)))},
        )
    # the wait parks on the push plane's DEDICATED poll pool (sized to
    # the subscriber bound), never the event loop and never the default
    # executor the batching engine dispatches through — parked polls
    # must not starve the scoring that would wake them
    results, dropped = await asyncio.get_running_loop().run_in_executor(
        plane.poll_executor, broker.poll, subscriber, target, timeout
    )
    return web.json_response(
        {
            "subscriber": subscriber,
            "target": target,
            "results": results,
            "dropped": dropped,
        }
    )


@routes.post("/gordo/v0/{project}/adapt")
async def adapt(request: web.Request) -> web.Response:
    """Apply the online adaptation: recalibrate (default) or
    incrementally refit the drifted members (or an explicit ``targets``
    list) and land the result as a new bank generation through the
    zero-downtime swap. Body (optional JSON):
    ``{"mode": "recalibrate"|"refit", "targets": ["name", ...]}``.
    A failed adaptation rolls back completely — the serving generation
    is untouched — and answers 500 with ``rolled_back``."""
    plane = _stream_plane(request)
    mode, targets = "recalibrate", None
    if request.can_read_body:
        try:
            body = await request.json()
        except Exception:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "expected a JSON body"}),
                content_type="application/json",
            )
        if isinstance(body, dict):
            mode = body.get("mode", "recalibrate")
            targets = body.get("targets")
        elif body:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": "expected a JSON object body"}),
                content_type="application/json",
            )
    if mode not in ("recalibrate", "refit"):
        raise web.HTTPBadRequest(
            text=json.dumps({"error": f"mode must be recalibrate|refit, got {mode!r}"}),
            content_type="application/json",
        )
    if targets is not None and not isinstance(targets, list):
        raise web.HTTPBadRequest(
            text=json.dumps({"error": "targets must be a list"}),
            content_type="application/json",
        )
    try:
        result = await plane.adapt(mode, targets=targets)
    except Exception as exc:
        # the rollback contract already ran (streaming/adapt.py): the
        # serving generation and the published models are untouched
        logger.exception("adaptation failed (rolled back)")
        return web.json_response(
            {
                "error": f"{type(exc).__name__}: {exc}",
                "rolled_back": True,
                "generation": int(request.app.get("bank_generation", 0)),
                "request_id": request.get("request_id"),
            },
            status=500,
        )
    return web.json_response(result)


@routes.get("/gordo/v0/{project}/{target}/healthcheck")
async def healthcheck(request: web.Request) -> web.Response:
    _get_model(request)
    return web.json_response({"gordo-server-version": __version__})


@routes.get("/gordo/v0/{project}/{target}/metadata")
async def metadata(request: web.Request) -> web.Response:
    _, meta = _get_model(request)
    return web.json_response(
        {"endpoint-metadata": meta, "env": {"model_collection_dir": _collection(request).root}}
    )


@routes.get("/gordo/v0/{project}/{target}/download-model")
async def download_model(request: web.Request) -> web.Response:
    model, _ = _get_model(request)
    data = serializer.dumps(model)
    return web.Response(
        body=data, content_type="application/octet-stream"
    )


async def _parse_request(request: web.Request):
    content_type = request.content_type or "application/json"
    if "parquet" in content_type:
        if not _PARQUET_OK:
            # a clean 415 (instead of an ImportError 500) lets the bulk
            # client downgrade the run to JSON
            raise web.HTTPUnsupportedMediaType(
                text=json.dumps(
                    {"error": "no parquet engine installed on this server"}
                ),
                content_type="application/json",
            )
        raw = await request.read()
        return extract_x_y(None, raw, content_type)
    try:
        body = await request.json()
    except Exception:
        raise web.HTTPBadRequest(
            text=json.dumps({"error": "Expected JSON body with an X entry"}),
            content_type="application/json",
        )
    return extract_x_y(body)


async def _parse_scoring(request: web.Request):
    """Parse a scoring POST once, by encoding.

    Returns ``(encoding, X, y, Xf, yf)``: ``Xf``/``yf`` are the float32
    arrays scoring consumes (validated ONCE and reused by the finiteness
    breaker — the old second float64 copy in ``_note_scoring_result`` is
    gone); ``X``/``y`` DataFrames exist only on the JSON/parquet paths
    (``None`` for tensor — its fast path never builds one). The ``parse``
    stage span carries the encoding, so per-encoding parse cost is
    visible in traces (docs/observability.md)."""
    encoding = _request_encoding(request)
    trace = request.get("trace")
    t_parse = time.monotonic()
    X = y = yf = None
    if encoding == "tensor":
        raw = await request.read()
        try:
            # bytes -> frombuffer views -> float32 rows; no DataFrame,
            # no per-value boxing (server/model_io.py, utils/wire.py)
            Xf, yf, meta = decode_tensor_request_ex(raw)
        except WireFormatError as exc:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": f"tensor body: {exc}"}),
                content_type="application/json",
            )
        if meta:
            # binary-path QoS identity: the __meta__ sidecar overrides
            # the headers (qos/classify.py) — the FINAL value here is
            # what admission gates on and the ledger attributes
            request["qos"] = classify_meta(meta, request.get("qos"))
    else:
        try:
            X, y = await _parse_request(request)
        except ValueError as exc:
            raise web.HTTPBadRequest(
                text=json.dumps({"error": str(exc)}),
                content_type="application/json",
            )
        # no-copy when the parse already produced float32 (the old
        # .astype("float32") unconditionally copied per request)
        Xf = np.asarray(X.values, dtype="float32")
        if y is not None:
            yf = np.asarray(y.values, dtype="float32")
    if trace is not None:
        trace.add_span("parse", t_parse, time.monotonic(), encoding=encoding)
    return encoding, X, y, Xf, yf


@routes.post("/gordo/v0/{project}/{target}/prediction")
async def prediction(request: web.Request) -> web.Response:
    model, _ = _get_model(request)
    _quarantine_gate(request)
    target = request.match_info["target"]
    encoding, X, _y, Xf, _yf = await _parse_scoring(request)
    engine = _bank_engine(request)
    tenant_label, qos_class = _qos_admit(request, engine)
    trace = request.get("trace")
    deadline = request.get("deadline")
    try:
        if engine is not None:
            result = await _engine_score(engine)(
                target,
                Xf,
                request_id=request.get("request_id"),
                trace=trace,
                deadline=deadline,
                tenant=tenant_label,
                qos_class=qos_class,
            )
            output = result.model_output
            # goodput: the request's share of its group's device window
            # (bank-attributed), committed by the middleware on response
            request["device_s"] = result.device_s
        else:
            if deadline is not None and deadline.expired():
                # per-model path: the executor job can't be cancelled
                # once submitted, so the expiry check runs before it
                _note_deadline_expired_per_model(request)
                raise DeadlineExceeded("deadline expired before dispatch")
            loop = asyncio.get_running_loop()
            t0 = time.monotonic()
            output = await loop.run_in_executor(None, model.predict, Xf)
            request["device_s"] = time.monotonic() - t0
            if trace is not None:
                # per-model fallback path: no coalescing stages, but the
                # device work still gets its named span
                trace.add_span(
                    "device_execute", t0, t0 + request["device_s"],
                    path="per-model",
                )
    except EngineOverloaded as exc:
        raise _http_overloaded(exc)
    except DeadlineExceeded as exc:
        # NOT a scoring error: the model is healthy, the clock ran out —
        # never counted against the quarantine breaker
        raise _http_deadline_exceeded(request, exc)
    except Exception as exc:  # surface model errors as 400s with detail
        _note_scoring_error(request, target, exc)
        logger.exception("prediction failed")
        raise web.HTTPBadRequest(
            text=json.dumps({"error": f"{type(exc).__name__}: {exc}"}),
            content_type="application/json",
        )
    _note_scoring_result(request, target, Xf, output)
    if encoding == "tensor":
        # binary out for binary in: the output array is framed into one
        # preallocated body — no tolist, no index stringification (the
        # client trims its own index by the offset in __meta__)
        return web.Response(
            body=encode_prediction_response(output, len(Xf)),
            content_type=TENSOR_CONTENT_TYPE,
        )
    out_index = X.index[len(X) - len(output):]
    return web.json_response(
        {
            "data": np.asarray(output).tolist(),
            "index": [str(i) for i in out_index],
        }
    )


@routes.post("/gordo/v0/{project}/{target}/anomaly/prediction")
async def anomaly_prediction(request: web.Request) -> web.Response:
    model, _ = _get_model(request)
    if not hasattr(model, "anomaly"):
        raise web.HTTPUnprocessableEntity(
            text=json.dumps({"error": "Model does not support anomaly scoring"}),
            content_type="application/json",
        )
    _quarantine_gate(request)
    target = request.match_info["target"]
    encoding, X, y, Xf, yf = await _parse_scoring(request)
    engine = _bank_engine(request)
    tenant_label, qos_class = _qos_admit(request, engine)
    trace = request.get("trace")
    deadline = request.get("deadline")
    frame = None
    try:
        if engine is not None:
            result = await _engine_score(engine)(
                target,
                Xf,
                yf,
                request_id=request.get("request_id"),
                trace=trace,
                deadline=deadline,
                tenant=tenant_label,
                qos_class=qos_class,
            )
            request["device_s"] = result.device_s
            t0 = time.monotonic()
            if encoding == "tensor":
                # the banked fast path end-to-end: fetched device buffers
                # -> ScoreResult arrays -> one preallocated response
                # body. No DataFrame is ever constructed on this path.
                body = encode_anomaly_response(
                    result.tags, result.to_arrays(), result.offset
                )
                total_scaled = result.total_scaled
                if trace is not None:
                    trace.add_span(
                        "postprocess", t0, time.monotonic(), stage="to_wire"
                    )
            else:
                frame = result.to_frame(index=X.index)
                if trace is not None:
                    trace.add_span(
                        "postprocess", t0, time.monotonic(), stage="to_frame"
                    )
        else:
            if deadline is not None and deadline.expired():
                _note_deadline_expired_per_model(request)
                raise DeadlineExceeded("deadline expired before dispatch")
            if X is None:
                # per-model fallback wants DataFrames (model.anomaly's
                # contract); tensor callers pay one cheap wrap here —
                # the hot banked path above never does
                X = pd.DataFrame(Xf)
                y = None if yf is None else pd.DataFrame(yf)
            loop = asyncio.get_running_loop()
            t0 = time.monotonic()
            frame = await loop.run_in_executor(None, model.anomaly, X, y)
            request["device_s"] = time.monotonic() - t0
            if trace is not None:
                trace.add_span(
                    "device_execute", t0, t0 + request["device_s"],
                    path="per-model",
                )
            if encoding == "tensor":
                body = encode_anomaly_response(
                    frame["model-input"].columns,
                    anomaly_frame_arrays(frame),
                    len(Xf) - len(frame),
                )
    except EngineOverloaded as exc:
        raise _http_overloaded(exc)
    except DeadlineExceeded as exc:
        raise _http_deadline_exceeded(request, exc)
    except Exception as exc:
        _note_scoring_error(request, target, exc)
        logger.exception("anomaly scoring failed")
        raise web.HTTPBadRequest(
            text=json.dumps({"error": f"{type(exc).__name__}: {exc}"}),
            content_type="application/json",
        )
    # NaN anywhere in the model's reconstruction propagates into the
    # total columns (sums of NaN), so the totals are a cheap O(rows)
    # whole-frame finiteness proxy for the breaker
    if frame is not None:
        total_scaled = frame[("total-anomaly-scaled", "")].to_numpy()
    _note_scoring_result(request, target, Xf, total_scaled)
    if encoding == "tensor":
        return web.Response(body=body, content_type=TENSOR_CONTENT_TYPE)
    return web.json_response(frame_to_dict(frame))
