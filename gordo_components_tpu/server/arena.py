"""Shape-keyed padded-buffer arena for the bank's coalesced hot loop.

``ModelBank.score_many`` used to allocate (and zero) a fresh
``np.zeros((B, T, F))`` pair for every bucket-group dispatch — at the
north-star request mix that is megabytes of allocator churn per call,
and round-5 profiling flagged it as the top host cost in the coalesced
loop. The arena keeps a bounded LRU pool of scratch buffers keyed by
exact shape+dtype: a hit returns a *dirty* buffer (the caller overwrites
the data region with real rows and zeroes only the pad tail), a miss
allocates a fresh zeroed one. Pool size is bounded by
``GORDO_ARENA_MAX_MB`` (default 256; ``0`` disables pooling entirely —
every acquire is a plain ``np.zeros`` and the arena keeps no state,
which is also the serial-parity baseline the pipeline tests compare
against).

Thread-safety: acquire/release take one lock around dict ops only — the
fill loop (the actual hot part) runs lock-free on the caller's buffer.
Buffers are returned by the pipeline only after the group's outputs are
fetched, so a pooled buffer is never handed to a new request while a
device computation could still read it.
"""

import os
import threading
from collections import OrderedDict
from typing import Dict, List, Tuple

import numpy as np

__all__ = ["PaddedArena", "DEFAULT_MAX_MB"]

DEFAULT_MAX_MB = 256.0


def _env_max_bytes() -> int:
    raw = os.environ.get("GORDO_ARENA_MAX_MB")
    if raw is None:
        return int(DEFAULT_MAX_MB * 1024 * 1024)
    try:
        return int(float(raw) * 1024 * 1024)
    except ValueError:
        raise ValueError(
            f"GORDO_ARENA_MAX_MB must be a number of megabytes, got {raw!r}"
        ) from None


class PaddedArena:
    """Bounded LRU pool of reusable padded scratch buffers.

    ``acquire(shape)`` returns ``(buffer, clean)``: ``clean`` is True for
    a freshly zeroed allocation (pool miss, or pooling disabled) and
    False for a reused buffer whose pad regions the caller must zero.
    ``release(buffer)`` returns it to the pool, evicting
    least-recently-used *shapes* while the pooled bytes exceed the
    budget. ``outstanding`` counts acquired-but-unreleased buffers — the
    leak detector the chaos tests assert back to zero.
    """

    def __init__(self, max_bytes: int = None):
        self.max_bytes = _env_max_bytes() if max_bytes is None else int(max_bytes)
        # shape/dtype key -> stack of free buffers; OrderedDict order is
        # recency (most recently used at the end)
        self._pool: "OrderedDict[Tuple[tuple, str], List[np.ndarray]]" = (
            OrderedDict()
        )
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.pooled_bytes = 0
        self.outstanding = 0

    @property
    def enabled(self) -> bool:
        return self.max_bytes > 0

    def acquire(self, shape, dtype=np.float32):
        if self.max_bytes <= 0:
            return np.zeros(shape, dtype), True
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            stack = self._pool.get(key)
            if stack:
                buf = stack.pop()
                if not stack:
                    del self._pool[key]
                else:
                    self._pool.move_to_end(key)
                self.pooled_bytes -= buf.nbytes
                self.hits += 1
                self.outstanding += 1
                return buf, False
        # allocate outside the lock (np.zeros is the expensive part) and
        # count only a SUCCESSFUL allocation: a MemoryError here must not
        # strand the outstanding counter the leak detectors assert on
        buf = np.zeros(shape, dtype)
        with self._lock:
            self.misses += 1
            self.outstanding += 1
        return buf, True

    def release(self, buf: np.ndarray) -> None:
        if self.max_bytes <= 0:
            return
        key = (buf.shape, buf.dtype.str)
        with self._lock:
            self.outstanding -= 1
            if buf.nbytes > self.max_bytes:
                # a single buffer larger than the whole budget is simply
                # not pooled: admitting it would evict every OTHER shape
                # from the pool before the budget check reached it
                self.evictions += 1
                return
            self._pool.setdefault(key, []).append(buf)
            self._pool.move_to_end(key)
            self.pooled_bytes += buf.nbytes
            # evict least-recently-used shapes until back under budget
            while self.pooled_bytes > self.max_bytes and self._pool:
                k, stack = next(iter(self._pool.items()))
                victim = stack.pop()
                if not stack:
                    del self._pool[k]
                self.pooled_bytes -= victim.nbytes
                self.evictions += 1

    def stats(self) -> Dict[str, object]:
        # under the lock: /stats scrapes read this from the event-loop
        # thread while the scoring executor mutates the pool, and an
        # unlocked dict iteration can raise mid-resize
        with self._lock:
            total = self.hits + self.misses
            return {
                "enabled": self.enabled,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else None,
                "evictions": self.evictions,
                "pooled_bytes": self.pooled_bytes,
                "pooled_buffers": sum(len(s) for s in self._pool.values()),
                "outstanding": self.outstanding,
            }
