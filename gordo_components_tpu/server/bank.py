"""HBM-resident model bank: many models, one compiled program per bucket.

The reference serves one model per Flask process (gordo_components/server,
unverified; SURVEY.md §2 "server") — scoring N machines means N processes
each holding one Keras graph. The TPU-native inversion (BASELINE.json
config 5, SURVEY.md §7 stage 5): every *bankable* model in the collection
is stacked into one params pytree per (kind, n_features, architecture)
bucket, resident in device HBM. A request for any model becomes an indexed
gather into the stack inside a single jit'd scoring program, so

- loading 1,000 models costs one ``device_put`` per bucket, not 1,000
  processes;
- concurrent requests for *different* models coalesce into one batched XLA
  call (see :class:`BatchingEngine`) — the MXU sees (B, T, F) matmuls
  instead of B separate (T, F) launches;
- request shapes are bucketed to powers of two so the number of compiled
  programs stays O(log(max_rows) * log(max_batch)) regardless of traffic.

Bankable = DiffBasedAnomalyDetector over any zoo estimator (feedforward,
LSTM, forecast, conv — sequence windowing runs in-graph per bucket with
its static lookback) with any chain of affine scalers in front. Bespoke
pipelines (non-affine preprocessing, custom estimator classes) fall back
to the per-model scoring path in views.py — same response schema either
way, via the shared ``assemble_anomaly_frame`` — and the fallback set is
surfaced per model through ``ModelBank.coverage`` and ``GET /models``.
"""

import asyncio
import contextlib
import functools
import inspect
import json
import logging
import os
import time
import weakref
from collections import deque
from concurrent.futures import Future as ConcurrentFuture
from concurrent.futures import InvalidStateError as ConcurrentInvalidState
from concurrent.futures import TimeoutError as FuturesTimeoutError
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gordo_components_tpu.models.anomaly.diff import (
    DiffBasedAnomalyDetector,
    assemble_anomaly_frame,
)
from gordo_components_tpu.models.register import lookup_factory
from gordo_components_tpu.models.train_core import _next_pow2
from gordo_components_tpu.observability import get_registry
from gordo_components_tpu.observability.cost import estimate_flops_per_row
from gordo_components_tpu.ops.pallas_score import (
    banked_anomaly_score,
    resolve_bank_kernel_mode,
)
from gordo_components_tpu.ops.quantize import (
    dequantize_params,
    normalize_bank_dtype,
    quantize_stacked,
    tree_weight_bytes,
)
from gordo_components_tpu.ops.scaler import ScalerParams
from gordo_components_tpu.ops.seq_scan import (
    lstm_time_major_forward,
    resolve_seq_kernel_mode,
    resolve_seq_layout,
    supports_time_major,
)
from gordo_components_tpu.resilience.deadline import Deadline, DeadlineExceeded
from gordo_components_tpu.resilience.faults import faultpoint
from gordo_components_tpu.server.arena import PaddedArena

logger = logging.getLogger(__name__)

# chaos sites (tests/test_chaos.py): bucket stack/compile, low-precision
# weight quantization, batched scoring dispatch, and engine admission.
# Module-level points so the disabled cost on the serving hot loop is one
# attribute check (see the 5% guard test).
_FP_FINALIZE = faultpoint("bank.finalize")
_FP_QUANTIZE = faultpoint("bank.quantize")
_FP_SCORE = faultpoint("bank.score")
_FP_ENGINE_QUEUE = faultpoint("engine.queue")

# short dtype tags for bucket metric labels (bounded, readable)
_DTYPE_TAGS = {"bfloat16": "bf16", "int8": "int8"}


# --------------------------------------------------------------------- #
# extraction: estimator object -> bankable pieces
# --------------------------------------------------------------------- #


@dataclass
class _BankEntry:
    name: str
    registry_type: str  # estimator class name -> factory registry
    kind: str
    factory_kwargs: Dict[str, Any]
    compute_dtype: str
    n_features: int
    lookback: int  # 1 for feedforward
    target_offset: int  # sequence models: 0 reconstruct, 1 forecast
    params: Any  # numpy pytree
    in_shift: np.ndarray
    in_scale: np.ndarray
    err_shift: np.ndarray
    err_scale: np.ndarray


def _affine_from_scaler(step, n_features: int):
    """Return (shift, scale) arrays for a supported scaler step, or None.

    Supports the JAX scalers (already affine) and sklearn's affine family —
    MinMaxScaler (``x*scale_ + min_`` == ``(x - (-min_/scale_)) * scale_``),
    StandardScaler, RobustScaler, MaxAbsScaler.
    """
    params = getattr(step, "scaler_params_", None)
    if params is not None:  # JaxMinMaxScaler / JaxStandardScaler
        return np.asarray(params.shift), np.asarray(params.scale)
    cls = type(step).__name__
    if cls == "MinMaxScaler" and getattr(step, "scale_", None) is not None:
        scale = np.asarray(step.scale_, np.float32)
        return (-np.asarray(step.min_, np.float32) / scale), scale
    # StandardScaler/RobustScaler both compute (x - shift) / scale_, with
    # the respective attribute set to None when centering/scaling is off
    shift_attr = {"StandardScaler": "mean_", "RobustScaler": "center_"}.get(cls)
    if shift_attr and hasattr(step, "scale_"):
        center = getattr(step, shift_attr, None)
        shift = np.asarray(
            center if center is not None else np.zeros(n_features), np.float32
        )
        scale_ = step.scale_
        if scale_ is None:
            return shift, np.ones((n_features,), np.float32)
        return shift, 1.0 / np.asarray(scale_, np.float32)
    if cls == "MaxAbsScaler" and getattr(step, "scale_", None) is not None:
        return (
            np.zeros((n_features,), np.float32),
            1.0 / np.asarray(step.scale_, np.float32),
        )
    return None


# estimator classes whose scoring the bank can reproduce exactly; the
# registry type doubles as the factory namespace (models/register.py)
_BANKABLE_TYPES = {"AutoEncoder", "LSTMAutoEncoder", "LSTMForecast", "ConvAutoEncoder"}


def _extract_entry(name: str, model) -> Tuple[Optional[_BankEntry], Optional[str]]:
    """Decompose a served model into bank pieces.

    Returns ``(entry, None)`` when bankable, else ``(None, reason)`` — the
    reason is surfaced through :meth:`ModelBank.coverage` so an operator
    can see exactly which models fell back to the per-model path and why.
    """
    if not isinstance(model, DiffBasedAnomalyDetector):
        return None, f"not a DiffBasedAnomalyDetector ({type(model).__name__})"
    if model.error_scaler_ is None:
        return None, "detector is unfitted (no error scaler)"
    base = model.base_estimator
    pre_steps: Sequence = []
    if hasattr(base, "steps"):
        pre_steps, est = base.steps[:-1], base.steps[-1][1]
    else:
        est = base
    registry_type = type(est).__name__
    if registry_type not in _BANKABLE_TYPES:
        return None, f"unsupported estimator class {registry_type}"
    if getattr(est, "params_", None) is None:
        return None, "estimator is unfitted"
    n_features = est.n_features_
    # compose the (possibly chained) affine scalers into one:
    # t(x) = (x - in_shift) * in_scale; appending ((t - s) * k) gives
    # (x - (in_shift + s/in_scale)) * (in_scale * k)
    in_shift = np.zeros((n_features,), np.float32)
    in_scale = np.ones((n_features,), np.float32)
    for step_name, step in pre_steps:
        aff = _affine_from_scaler(step, n_features)
        if aff is None:
            return None, f"non-affine preprocessing step {step_name!r}"
        s, k = np.asarray(aff[0], np.float32), np.asarray(aff[1], np.float32)
        safe_scale = np.where(in_scale == 0, 1.0, in_scale)
        in_shift = in_shift + s / safe_scale
        in_scale = in_scale * k
    err = ScalerParams(*model.error_scaler_)
    return (
        _BankEntry(
            name=name,
            registry_type=registry_type,
            kind=est.kind,
            factory_kwargs=dict(est.factory_kwargs),
            compute_dtype=getattr(est, "compute_dtype", "float32"),
            n_features=int(n_features),
            lookback=int(getattr(est, "lookback_window", 1)),
            target_offset=int(getattr(est, "_target_offset", 0)),
            params=jax.tree.map(np.asarray, est.params_),
            in_shift=in_shift.astype(np.float32),
            in_scale=in_scale.astype(np.float32),
            err_shift=np.asarray(err.shift, np.float32),
            err_scale=np.asarray(err.scale, np.float32),
        ),
        None,
    )


# --------------------------------------------------------------------- #
# bucket: stacked device state + compiled scoring program
# --------------------------------------------------------------------- #


def _prev_pow2(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


class _Bucket:
    """All models sharing (type, kind, n_features, lookback, factory
    kwargs, dtype): one stacked params pytree + scaler stacks in HBM, one
    scoring fn reused for every (batch, rows) shape bucket.

    Sequence models bank too: windowing runs in-graph
    (``ops/windows.sliding_windows``) with the bucket's static lookback,
    and outputs carry the warm-up ``offset`` (output row i <- input row
    i + offset), exactly like the per-model path.

    With a ``mesh`` (1-D ``models`` axis, ``parallel/mesh.py``), the
    stacked params/scalers are placed under a ``NamedSharding`` on their
    leading (model) axis — the same layout ``FleetTrainer`` trains under
    (``parallel/fleet.py``) — so a D-chip server holds each model's
    weights exactly once. Requests are ROUTED: the host groups chunks by
    the shard that owns their model (the leading axis is split into D
    contiguous blocks), and a ``shard_map`` program scores each device's
    sub-batch against its local params with NO collectives — per-request
    compute stays local to the shard that owns the model, the total FLOPs
    equal the single-device program's, and the only cross-device traffic
    is the result fetch. (The alternative — replicating every request to
    all devices and masking — costs D× the FLOPs; routing costs one
    host-side groupby.)"""

    def __init__(
        self,
        kind: str,
        n_features: int,
        factory_kwargs: Dict[str, Any],
        compute_dtype: str = "float32",
        registry_type: str = "AutoEncoder",
        lookback: int = 1,
        target_offset: int = 0,
        mesh=None,
        bank_dtype: str = "float32",
        kernel_mode: str = "jnp",
    ):
        self.kind = kind
        self.n_features = n_features
        self.factory_kwargs = factory_kwargs
        self.compute_dtype = compute_dtype
        self.registry_type = registry_type
        self.lookback = int(lookback)
        self.target_offset = int(target_offset)
        # low-precision weight bank (ops/quantize.py): the REQUESTED
        # storage dtype; ``effective_dtype`` records what finalize
        # actually shipped to HBM (a failed quantization falls back to
        # fp32 for this bucket only, with the reason in quantize_error)
        self.bank_dtype = bank_dtype
        self.kernel_mode = kernel_mode
        self.effective_dtype = "float32"
        self.quantize_error: Optional[str] = None
        self.weight_bytes = 0  # stacked params bytes as stored (HBM cost)
        self.weight_bytes_fp32 = 0  # same stack at fp32 (the baseline)
        # short stable id for per-bucket metric labels (the full bucket key
        # is a JSON blob; labels need something bounded and readable). The
        # readable prefix alone is NOT unique — buckets differing only in
        # factory kwargs / dtype / target offset are separate compiled
        # programs and must not blend into one series — so those ride in
        # as a short content hash suffix when non-default.
        self.label = f"{registry_type}:{kind}:f{n_features}:l{self.lookback}"
        if self.target_offset:
            self.label += f":o{self.target_offset}"
        if bank_dtype != "float32":
            # storage dtype in the label: a bf16 bank and an fp32 bank
            # compile DIFFERENT programs over different HBM layouts and
            # must not blend into one metric series (bucket keying by
            # dtype; the tag stays even if quantization falls back, so
            # the fallback is visible as a q-tagged bucket serving fp32
            # alongside the gordo_bank_quantize_fallback_total counter)
            self.label += f":q{_DTYPE_TAGS.get(bank_dtype, bank_dtype)}"
        if factory_kwargs or compute_dtype != "float32":
            import hashlib

            extra = json.dumps(
                [sorted(factory_kwargs.items()), compute_dtype], default=str
            )
            self.label += ":" + hashlib.sha1(extra.encode()).hexdigest()[:6]
        self.mesh = mesh
        self.names: List[str] = []
        self._entries: List[_BankEntry] = []
        # device state, built by finalize()
        self.params = None
        self.scalers = None  # (in_shift, in_scale, err_shift, err_scale)
        self._score = None
        self.n_shards = 1  # mesh model-axis size after finalize()
        self.shard_size = 0  # models per shard (padded stack / n_shards)
        self._sharding = None  # NamedSharding on the model axis (mesh mode)
        # static cost-attribution feed (observability/cost.py), computed
        # once by finalize(): analytic forward FLOPs for one routed row
        # (one scoring window for sequence models) through this bucket's
        # compiled program
        self.flops_per_row = 0.0
        self.flops_method = "unknown"
        self.params_per_member = 0
        # sequence fast-path provenance, resolved by finalize()
        self.seq_layout = "legacy"
        self.seq_kernel = "jnp"

    @property
    def offset(self) -> int:
        return self.lookback - 1 + self.target_offset

    def add(self, entry: _BankEntry) -> None:
        self._entries.append(entry)
        self.names.append(entry.name)

    def finalize(self) -> None:
        _FP_FINALIZE.fire()
        entries = self._entries
        sharding = None
        if self.mesh is not None:
            from gordo_components_tpu.parallel.mesh import (
                MODEL_AXIS,
                pad_count_to_mesh,
                shard_model_axis,
            )

            self.n_shards = int(self.mesh.shape[MODEL_AXIS])
            # the leading axis must divide the mesh: pad by repeating the
            # last entry (real params — zero-padding would still be
            # correct, since no routed slot ever points at a pad row, but
            # repeats keep every row's numerics in-distribution)
            n_pad = pad_count_to_mesh(len(entries), self.mesh)
            entries = entries + [entries[-1]] * (n_pad - len(entries))
            self.shard_size = n_pad // self.n_shards
            sharding = self._sharding = shard_model_axis(self.mesh)
        stacked = jax.tree.map(
            lambda *leaves: np.stack(leaves), *[e.params for e in entries]
        )
        self.weight_bytes_fp32 = tree_weight_bytes(stacked)
        self.effective_dtype = "float32"
        if self.bank_dtype != "float32":
            # low-precision weight bank (ops/quantize.py): HBM holds the
            # bf16/int8 stack, the compiled program dequantizes the
            # gathered member back to fp32. A failed quantization is an
            # IMPAIRMENT of capacity, not of correctness — this bucket
            # falls back to fp32 storage (counted by the bank) instead of
            # failing the whole build.
            try:
                _FP_QUANTIZE.fire()
                stacked = quantize_stacked(stacked, self.bank_dtype)
                self.effective_dtype = self.bank_dtype
            except Exception as exc:
                self.quantize_error = f"{type(exc).__name__}: {exc}"
                logger.warning(
                    "Bucket %s: %s quantization failed (%s); storing fp32 "
                    "for this bucket",
                    self.label, self.bank_dtype, exc,
                )
        self.weight_bytes = tree_weight_bytes(stacked)
        self.params = jax.device_put(stacked, sharding)
        self.scalers = tuple(
            jax.device_put(np.stack([getattr(e, f) for e in entries]), sharding)
            for f in ("in_shift", "in_scale", "err_shift", "err_scale")
        )
        module = lookup_factory(self.registry_type, self.kind)(
            self.n_features, compute_dtype=self.compute_dtype, **self.factory_kwargs
        )
        # one member's param count + analytic FLOPs, once per compiled
        # program — the cost model joins these static numbers to the
        # ledger's measured device seconds (entries[0]: pad repeats share
        # the real members' shapes, so any entry works)
        self.params_per_member = int(
            sum(np.asarray(l).size for l in jax.tree.leaves(entries[0].params))
        )
        self.flops_per_row, self.flops_method = estimate_flops_per_row(
            module, self.n_features, self.lookback, self.params_per_member
        )
        lookback, t_off, off = self.lookback, self.target_offset, self.offset
        dequant = self.effective_dtype != "float32"
        kernel_mode = self.kernel_mode
        # sequence fast path (ops/seq_scan.py): LSTM buckets can score
        # through the time-major scan — batch slots become the member
        # axis, kept innermost — with the fused recurrent-step kernel
        # when GORDO_SEQ_KERNEL resolves to it. Resolved ONCE here (like
        # kernel_mode): the choice is baked into the compiled program.
        use_tm = (
            resolve_seq_layout() == "time_major"
            and lookback > 1
            and supports_time_major(module)
        )
        self.seq_layout = "time_major" if use_tm else "legacy"
        self.seq_kernel = resolve_seq_kernel_mode() if use_tm else "jnp"
        seq_kernel = self.seq_kernel
        if use_tm:
            self.flops_method += f":time_major(T={lookback})"

        def forward_tm(params, in_shift, in_scale, idx, X, Y):
            # idx: (B,) int32; X/Y: (B, T, F) raw-space. One gather
            # stacks every slot's member params; one scan over time
            # scores all slots' windows with the slot axis innermost.
            from gordo_components_tpu.ops.windows import sliding_windows

            p = jax.tree.map(lambda a: a[idx], params)
            if dequant:
                p = dequantize_params(p)
            sh = in_shift[idx][:, None, :]
            sc = in_scale[idx][:, None, :]
            xs = (X - sh) * sc
            ys = (Y - sh) * sc
            W = jax.vmap(lambda x: sliding_windows(x, lookback))(xs)
            if t_off:
                W = W[:, :-t_off]
            recon = lstm_time_major_forward(module, p, W, kernel=seq_kernel)
            target = ys[:, off : off + recon.shape[1]]
            return recon, target

        def forward(params, in_shift, in_scale, i, x, y):
            # i: () int32 into the (local) stack; x/y: (T, F) raw-space;
            # returns (recon, target) — the epilogue runs batched below
            from gordo_components_tpu.ops.windows import sliding_windows

            p = jax.tree.map(lambda a: a[i], params)
            if dequant:
                # per-member dequantization INSIDE the compiled program:
                # only the gathered member's weights round-trip to fp32,
                # compute accumulates in fp32 throughout
                p = dequantize_params(p)
            xs = (x - in_shift[i]) * in_scale[i]
            ys = (y - in_shift[i]) * in_scale[i]
            if lookback > 1:
                W = sliding_windows(xs, lookback)
                if t_off:
                    W = W[:-t_off]
                recon = module.apply(p, W)  # (T - off, F)
                target = ys[off : off + recon.shape[0]]
            else:
                recon = module.apply(p, xs)
                target = ys
            return recon, target

        if self.mesh is None:

            def score(params, in_shift, in_scale, err_shift, err_scale, idx, X, Y):
                # idx: (B,) int32; X/Y: (B, T, F) raw-space. The model
                # forward vmaps per member; the scoring epilogue (scale ->
                # reconstruction error -> row norms) runs over the WHOLE
                # batch in one banked pass — the Pallas kernel's
                # (member, row-tile) grid on TPU, identical jnp math
                # elsewhere (ops/pallas_score.banked_anomaly_score)
                if use_tm:
                    recon, target = forward_tm(
                        params, in_shift, in_scale, idx, X, Y
                    )
                else:
                    recon, target = jax.vmap(
                        lambda i, x, y: forward(
                            params, in_shift, in_scale, i, x, y
                        )
                    )(idx, X, Y)
                diff, scaled, tot_u, tot_s = banked_anomaly_score(
                    target, recon, err_shift, err_scale, idx, mode=kernel_mode
                )
                return recon, diff, scaled, tot_u, tot_s

        else:
            from jax.sharding import PartitionSpec as P

            from gordo_components_tpu.parallel.compat import shard_map

            from gordo_components_tpu.parallel.mesh import MODEL_AXIS

            spec = P(MODEL_AXIS)

            def score(params, in_shift, in_scale, err_shift, err_scale, idx, X, Y):
                # idx: (D, Blocal) LOCAL indices; X/Y: (D, Blocal, T, F);
                # leading axis sharded over the mesh — each device scores
                # its own sub-batch against its local (shard_size, ...)
                # params block; no collectives. The banked epilogue runs
                # per device on the local sub-batch with the LOCAL scaler
                # stack — the gather indices are already shard-local.
                def local(p, ish, isc, esh, esc, i, x, y):
                    if use_tm:
                        recon, target = forward_tm(
                            p, ish, isc, i[0], x[0], y[0]
                        )
                    else:
                        recon, target = jax.vmap(
                            lambda ii, xx, yy: forward(p, ish, isc, ii, xx, yy)
                        )(i[0], x[0], y[0])
                    out = (recon,) + banked_anomaly_score(
                        target, recon, esh, esc, i[0], mode=kernel_mode
                    )
                    return jax.tree.map(lambda t: t[None], out)

                # check_vma off: the program is collective-free by design
                # (every output row depends only on the local shard), and
                # the varying-axes checker rejects the LSTM scan's
                # unvarying initial carry under a varying input
                return shard_map(
                    local,
                    mesh=self.mesh,
                    in_specs=(spec,) * 8,
                    out_specs=spec,
                    check_vma=False,
                )(params, in_shift, in_scale, err_shift, err_scale, idx, X, Y)

        self._score = jax.jit(score)
        self._entries = []  # host copies no longer needed

    def score_batch(self, indices: np.ndarray, X: np.ndarray, Y: np.ndarray):
        """Single-device path. indices: (B,), X/Y: (B, T, F) — already
        padded to pow2 B and T."""
        return self._score(
            self.params, *self.scalers, jnp.asarray(indices), jnp.asarray(X),
            jnp.asarray(Y),
        )

    def score_batch_sharded(self, indices: np.ndarray, X: np.ndarray, Y: np.ndarray):
        """Mesh path. indices: (D, Blocal) LOCAL indices (into each
        device's shard), X/Y: (D, Blocal, T, F), routed by the caller so
        row d only references models owned by shard d."""
        sh = self._sharding  # built once in finalize()
        return self._score(
            self.params,
            *self.scalers,
            jax.device_put(np.ascontiguousarray(indices), sh),
            jax.device_put(np.ascontiguousarray(X), sh),
            jax.device_put(np.ascontiguousarray(Y), sh),
        )


# --------------------------------------------------------------------- #
# the bank
# --------------------------------------------------------------------- #


@dataclass
class ScoreResult:
    """Raw-space arrays for one request, sliced back to its true length.

    ``offset`` is the sequence warm-up: output row i corresponds to input
    row i + offset (0 for feedforward). ``model_input`` holds the FULL
    request; ``to_frame`` trims it (and the index) to the output rows,
    matching ``DiffBasedAnomalyDetector.anomaly``'s frame exactly."""

    tags: List[str]
    model_input: np.ndarray
    model_output: np.ndarray
    diff: np.ndarray
    scaled: np.ndarray
    total_unscaled: np.ndarray
    total_scaled: np.ndarray
    offset: int = 0
    # this request's share of its group's useful device window (seconds),
    # assigned when a goodput ledger is attached (observability/goodput.py)
    # — the HTTP layer commits it to the goodput/wasted cells once the
    # request's final outcome is known; 0.0 when accounting is off
    device_s: float = 0.0

    def to_frame(self, index=None):
        n_out = len(self.model_output)
        if index is not None:
            index = index[self.offset :][:n_out]
        return assemble_anomaly_frame(
            self.tags,
            self.model_input[self.offset :][:n_out],
            self.model_output,
            self.diff,
            self.scaled,
            self.total_unscaled,
            self.total_scaled,
            index,
        )

    def to_arrays(self) -> Dict[str, np.ndarray]:
        """ndarray-out counterpart of :meth:`to_frame` for the binary
        wire path (server/model_io.py): the same trimmed arrays a frame
        would hold, keyed by the frame's top-level column names — but as
        the fetched device buffers themselves, with no DataFrame
        assembly, no per-column ``tolist``, and no float64 upcast. The
        input trim is a view; everything else is returned as-is."""
        n_out = len(self.model_output)
        return {
            "model-input": np.asarray(self.model_input)[self.offset :][:n_out],
            "model-output": self.model_output,
            "tag-anomaly-unscaled": self.diff,
            "tag-anomaly-scaled": self.scaled,
            "total-anomaly-unscaled": self.total_unscaled,
            "total-anomaly-scaled": self.total_scaled,
        }


def _slice_single(outs, slot, n_out: int):
    """Single-chunk reassembly (the serving-path norm): one sliced copy
    per output array instead of concatenate machinery. The copy is
    deliberate: a view would pin the whole (B, T, ...) batch output alive
    as long as any one result is held, and would be read-only where the
    multi-chunk path returns writable arrays."""
    return tuple(a[slot][:n_out].copy() for a in outs)


def _concat_chunks(outs, slots, cis, valids, n_out: int):
    """Multi-chunk reassembly: each chunk contributes its VALID output
    rows (rows computed from real, unpadded input)."""
    return tuple(
        np.concatenate(
            [a[slots[ci]][:v] for ci, v in zip(cis, valids)], axis=0
        )[:n_out]
        for a in outs
    )


class _GroupRun:
    """One bucket group's trip through the scoring pipeline.

    Built by ``_host_prep`` (coalesce + pad into arena buffers), handed
    to ``_dispatch`` (async XLA call — ``out`` holds device arrays whose
    computation may still be in flight), finished by ``_postprocess``
    (fence, fetch, reassemble, release buffers). Keeping the whole group
    state in one object is what lets ``score_many`` hold several groups
    in flight at once."""

    __slots__ = (
        "bucket", "req_ids", "req_plans", "slots", "n_chunks",
        "Xb", "Yb", "idx", "score_fn", "out", "off", "group_traces",
        "t_group", "t_chunks", "t_pad", "t_dispatch", "t_ready",
        "t_device_done", "t_post", "profile_dir", "_bufs",
        "routed_rows", "total_rows", "shard_rows",
    )

    def __init__(self):
        self.out = None
        self.t_dispatch = 0.0
        self.t_ready = 0.0
        self.t_post = 0.0
        # goodput accounting feed (observability/goodput.py): real vs
        # dispatched rows for the padded-waste split, per shard
        self.routed_rows = 0
        self.total_rows = 0
        self.shard_rows: Tuple[Tuple[str, int, int], ...] = ()
        # earliest time the outputs were OBSERVED ready (polled at host
        # stage boundaries); 0.0 until then — the fence time is only an
        # upper bound that absorbs whatever host work ran in between
        self.t_device_done = 0.0
        self.profile_dir = None
        self._bufs = ()

    def poll_ready(self, now: float) -> None:
        """Stamp ``t_device_done`` if the device outputs have become
        ready — called at host stage boundaries so the overlap
        accounting sees device completion near when it happened instead
        of at the (possibly much later) fence."""
        if self.t_device_done or self.out is None:
            return
        try:
            if all(a.is_ready() for a in self.out):
                self.t_device_done = now
        except Exception:
            # no is_ready on this array type, or the async computation
            # already failed device-side: a poll must never raise — the
            # fence in _postprocess surfaces device errors inside the
            # owning group's handler, keeping per-group isolation intact
            pass

    def release(self, arena: PaddedArena) -> None:
        """Return the padded input buffers to the arena (idempotent)."""
        bufs, self._bufs = self._bufs, ()
        for buf in bufs:
            arena.release(buf)


class ModelBank:
    """Stacked scoring bank over a model collection (HBM-resident).

    ``mesh`` (optional, a 1-D ``models``-axis mesh from
    ``parallel/mesh.fleet_mesh``) shards every bucket's stacked state over
    the devices and routes requests to the owning shard — see
    :class:`_Bucket`. Without it the bank is single-device, exactly as
    before."""

    def __init__(
        self,
        max_rows_per_call: int = 8192,
        mesh=None,
        registry=None,
        inflight: Optional[int] = None,
        arena_max_mb: Optional[float] = None,
        bank_dtype: Optional[str] = None,
        bank_kernel: Optional[str] = None,
        ledger=None,
        heat=None,
    ):
        self.max_rows = int(max_rows_per_call)
        self.mesh = mesh
        # access-heat accountant (observability/heat.py): APP-level state
        # handed to every bank generation — a /reload or rebalance swap
        # changes which bank feeds it without resetting the decayed
        # history (the model_rows cumulative-loss fix). None = heat off,
        # one attribute check on the scoring path (GORDO_HEAT=0), held
        # by the tests/test_heat_cost.py hot-loop guard.
        self.heat = heat
        if heat is not None:
            heat.bind_bank(self)
        # goodput ledger (observability/goodput.py): when attached, each
        # bucket group's device window, padded-row split, and host stage
        # seconds are accounted, and every ScoreResult carries its share
        # of the useful device window (device_s). None = accounting off,
        # one attribute check on the scoring path (the GORDO_SLO=0
        # contract, held by the tests/test_goodput.py hot-loop guard)
        self.ledger = ledger
        # low-precision weight bank (ops/quantize.py): storage dtype for
        # the stacked bucket params (env GORDO_BANK_DTYPE, default
        # float32 — the bitwise-parity baseline; bf16 halves and int8
        # ~quarters HBM per member, with the error budget documented in
        # docs/operations.md "Precision & capacity tuning")
        if bank_dtype is None:
            bank_dtype = os.environ.get("GORDO_BANK_DTYPE", "float32")
        self.bank_dtype = normalize_bank_dtype(bank_dtype)
        # banked epilogue dispatch (env GORDO_BANK_KERNEL, default auto:
        # the fused Pallas kernel on TPU, identical jnp math elsewhere) —
        # resolved ONCE here, baked into every bucket's compiled program
        self.kernel_mode = resolve_bank_kernel_mode(bank_kernel)
        # bucket label -> reason, for buckets whose low-precision
        # quantization failed and fell back to fp32 storage (capacity
        # impairment, surfaced via /stats bank_capacity + the
        # gordo_bank_quantize_fallback_total counter)
        self.quantize_fallbacks: Dict[str, str] = {}
        # pipeline depth: how many bucket groups may be in flight on the
        # device at once (env GORDO_BANK_INFLIGHT, default 2). While
        # group k executes, group k+1 is padded on the host and group
        # k-1's outputs are fetched — 1 disables the overlap (serial
        # prep->dispatch->fetch per group, the parity baseline).
        if inflight is None:
            raw = os.environ.get("GORDO_BANK_INFLIGHT", "2")
            try:
                inflight = int(raw)
            except ValueError:
                raise ValueError(
                    f"GORDO_BANK_INFLIGHT must be an integer, got {raw!r}"
                ) from None
        self._inflight_window = max(1, int(inflight))
        self._inflight_now = 0
        self.arena = PaddedArena(
            None if arena_max_mb is None else int(arena_max_mb * 1024 * 1024)
        )
        # host/device overlap accounting, aggregated across multi-group
        # calls: device_busy sums the non-overlapping per-group device
        # windows, wall the whole call — their ratio is the overlap the
        # pipeline buys (serial padding+fetching shows up as ratio << 1)
        self._pipe = {
            "calls": 0,
            "multi_group_calls": 0,
            "wall_s": 0.0,
            "device_busy_s": 0.0,
        }
        self._buckets: Dict[str, _Bucket] = {}
        self._index: Dict[str, Tuple[str, int]] = {}  # name -> (bucket_key, i)
        self._tags: Dict[str, List[str]] = {}
        # bank generation: bumped by the placement control plane's swap
        # (placement/swap.py) every time a rebuilt bank replaces this one
        # — exported as gordo_bank_generation, 0 for the boot bank
        self.generation = 0
        # per-model routed rows, the placement planner's load signal
        # (placement/planner.py): one dict get+set per request against a
        # multi-ms scoring dispatch. Set to None to disable entirely
        # (the rebalance hot-loop overhead guard's control arm).
        self.model_rows: Optional[Dict[str, int]] = {}
        # name -> human-readable reason the model serves per-model instead
        self.fallback: Dict[str, str] = {}
        # bucket label -> error for buckets whose finalize (stack/compile)
        # failed: those members still serve via the per-model path, but
        # unlike the by-design fallback set this is an IMPAIRMENT —
        # /healthz reports degraded while any entry is present
        self.finalize_failures: Dict[str, str] = {}
        # metrics registry (observability/): None = process default,
        # False = uninstrumented (the hot-loop overhead guard's control).
        # The router records per-shard routed/padded-row counters here —
        # the per-shard visibility VERDICT r5 weak #2 flagged as missing
        # (a hot model concentrates traffic on one shard while the others
        # idle, and nothing surfaced it).
        if registry is None:
            registry = get_registry()
        elif registry is False:
            registry = None
        self.registry = registry
        if registry is not None:
            self._m_shard_rows = registry.counter(
                "gordo_bank_shard_routed_rows_total",
                "Input rows routed to each model-axis shard",
                ("shard",),
            )
            self._m_shard_pad = registry.counter(
                "gordo_bank_shard_padded_rows_total",
                "Pad rows dispatched to each shard (batch padded to the max "
                "per-shard load; high on one shard = skewed routing)",
                ("shard",),
            )
            self._m_shard_reqs = registry.counter(
                "gordo_bank_shard_requests_total",
                "Request chunks routed to each shard",
                ("shard",),
            )
            self._m_bucket_calls = registry.counter(
                "gordo_bank_bucket_calls_total",
                "Batched XLA scoring dispatches per bucket",
                ("bucket",),
            )
            self._m_bucket_reqs = registry.counter(
                "gordo_bank_bucket_requests_total",
                "Requests scored per bucket",
                ("bucket",),
            )
            self._m_bucket_batch = registry.histogram(
                "gordo_bank_bucket_batch_size",
                "Coalesced chunks per batched XLA call, per bucket",
                ("bucket",),
                lo=1.0,
                hi=1e5,
            )
            self._m_quant_fallback = registry.counter(
                "gordo_bank_quantize_fallback_total",
                "Bucket quantizations that failed and fell back to fp32 "
                "storage (capacity impairment, not a correctness one)",
                ("bucket",),
            )
            # weakref: these read-through closures live in a potentially
            # process-global registry; a strong self capture would pin a
            # discarded bank's stacked params (GBs at fleet scale) forever
            ref = weakref.ref(self)
            registry.gauge(
                "gordo_bank_models", "Models resident in the HBM bank"
            ).labels().set_function(
                lambda: len(b._index) if (b := ref()) is not None else 0
            )
            registry.gauge(
                "gordo_bank_buckets", "Compiled bucket programs in the bank"
            ).labels().set_function(
                lambda: len(b._buckets) if (b := ref()) is not None else 0
            )

            # pipeline/arena series, read-through from the live counters
            # (stability contract, docs/observability.md). A collector —
            # not mirrored cells — so the hot loop pays nothing beyond
            # the plain-int increments it already makes; keyed so a
            # /reload's rebuilt bank replaces the old bank's emission.
            # The series carry the replaced bank's values: a /reload
            # passes the same registry exactly so counters stay
            # monotonic, and a scrape must never see hits/misses drop
            # back to zero. The predecessor's values stay LIVE (re-read
            # from its collector at render time) while the old bank is
            # still serving during the reload's construct+warmup window
            # — its gauges (pooled bytes, in-flight groups) are summed
            # in so that window doesn't mask a working pipeline — and
            # once the old bank is collected the counter baseline
            # freezes at its last observed values while the gauge
            # contribution drops to zero (gauges are point-in-time).
            base = {
                "hits": 0, "misses": 0, "bytes": 0, "inflight": 0,
                "prev": registry.get_collector("bank_pipeline"),
            }

            def _refresh_base():
                prev = base["prev"]
                if prev is None:
                    return
                rows = ()
                with contextlib.suppress(Exception):
                    rows = tuple(prev())
                if not rows:
                    # predecessor bank was GC'd (its collector yields
                    # nothing): freeze the counter baseline, zero the
                    # gauge carry, and drop the chain link so renders
                    # stop walking dead closures
                    base["prev"] = None
                    base["bytes"] = base["inflight"] = 0
                    return
                for pname, _t, _h, _l, pval in rows:
                    if pname == "gordo_bank_arena_hits_total":
                        base["hits"] = int(pval)
                    elif pname == "gordo_bank_arena_misses_total":
                        base["misses"] = int(pval)
                    elif pname == "gordo_bank_arena_bytes":
                        base["bytes"] = int(pval)
                    elif pname == "gordo_bank_inflight_groups":
                        base["inflight"] = int(pval)

            _refresh_base()

            def _pipeline_collect():
                bank = ref()
                if bank is None:
                    return ()
                _refresh_base()
                arena = bank.arena
                return (
                    (
                        "gordo_bank_arena_hits_total", "counter",
                        "Padded-buffer arena reuses on the coalesced loop",
                        {}, base["hits"] + arena.hits,
                    ),
                    (
                        "gordo_bank_arena_misses_total", "counter",
                        "Padded-buffer arena allocations (pool miss)",
                        {}, base["misses"] + arena.misses,
                    ),
                    (
                        "gordo_bank_arena_bytes", "gauge",
                        "Bytes held in the padded-buffer arena pool",
                        {}, base["bytes"] + arena.pooled_bytes,
                    ),
                    (
                        "gordo_bank_inflight_groups", "gauge",
                        "Bucket groups currently in flight in the scoring "
                        "pipeline", {}, base["inflight"] + bank._inflight_now,
                    ),
                )

            registry.collector(_pipeline_collect, key="bank_pipeline")

            def _capacity_collect():
                # per-dtype HBM weight bytes + models-per-GB, read from
                # the live buckets at render time (gauges are point-in-
                # time: a /reload's replacement collector under the same
                # key simply takes over). One capacity_stats() call is
                # the single source for both series — no second
                # aggregation to drift from it.
                bank = ref()
                if bank is None:
                    return ()
                cap = bank.capacity_stats()
                rows = [
                    (
                        "gordo_bank_weight_bytes", "gauge",
                        "Stacked bank weight bytes resident in HBM, by "
                        "storage dtype",
                        {"dtype": d}, nbytes,
                    )
                    for d, nbytes in sorted(
                        cap["weight_bytes_by_dtype"].items()
                    )
                ]
                if cap["models_per_gb"] is not None:
                    rows.append(
                        (
                            "gordo_bank_models_per_gb", "gauge",
                            "Bank members per GB of stacked-weight HBM at "
                            "the current dtype mix",
                            {}, cap["models_per_gb"],
                        )
                    )
                return tuple(rows)

            registry.collector(_capacity_collect, key="bank_capacity")
        else:
            # all of them, not just the one score_many guards on: a future
            # call site guarding on its own attribute must get None, not
            # AttributeError only in the registry=False configuration
            self._m_shard_rows = self._m_shard_pad = self._m_shard_reqs = None
            self._m_bucket_calls = self._m_bucket_reqs = None
            self._m_bucket_batch = self._m_quant_fallback = None

    # -------------------------- construction -------------------------- #

    @classmethod
    def from_models(cls, models: Dict[str, Any], **kwargs) -> "ModelBank":
        bank = cls(**kwargs)
        for name, model in models.items():
            try:
                entry, reason = _extract_entry(name, model)
            except Exception as exc:
                # one malformed model must not abort bank construction for
                # the whole collection (this runs at server startup and in
                # /reload); the model still serves via the per-model path
                logger.warning(
                    "Model %r: bank extraction failed; per-model path",
                    name,
                    exc_info=True,
                )
                bank.fallback[name] = f"extraction error: {type(exc).__name__}: {exc}"
                continue
            if entry is None:
                logger.debug("Model %r not bankable (%s); per-model path", name, reason)
                bank.fallback[name] = reason or "not bankable"
                continue
            key = json.dumps(
                [
                    entry.registry_type,
                    entry.kind,
                    entry.n_features,
                    entry.lookback,
                    entry.target_offset,
                    entry.compute_dtype,
                    sorted(entry.factory_kwargs.items()),
                    # storage dtype is part of the bucket identity: an
                    # fp32 and a bf16 stack are different HBM layouts
                    # compiled into different programs
                    bank.bank_dtype,
                ],
                default=str,
            )
            bucket = bank._buckets.get(key)
            if bucket is None:
                bucket = bank._buckets[key] = _Bucket(
                    entry.kind,
                    entry.n_features,
                    entry.factory_kwargs,
                    compute_dtype=entry.compute_dtype,
                    registry_type=entry.registry_type,
                    lookback=entry.lookback,
                    target_offset=entry.target_offset,
                    mesh=bank.mesh,
                    bank_dtype=bank.bank_dtype,
                    kernel_mode=bank.kernel_mode,
                )
            bank._index[name] = (key, len(bucket.names))
            bucket.add(entry)
            tags = getattr(models[name], "tags_", None)
            bank._tags[name] = (
                list(tags) if tags else [f"feature-{i}" for i in range(entry.n_features)]
            )
        # per-bucket finalize isolation: one bucket whose stack/compile
        # fails (OOM on a huge stack, a factory bug for one architecture,
        # an injected fault) must not abort bank construction — its
        # members fall back to the per-model scoring path with the reason
        # surfaced through coverage()/GET /models, and every OTHER bucket
        # still serves from HBM
        for key in list(bank._buckets):
            bucket = bank._buckets[key]
            try:
                bucket.finalize()
                if bucket.quantize_error is not None:
                    # the bucket SERVES (fp32 storage), but the capacity
                    # win was lost for its members — counted and surfaced
                    # so an operator sees a quarter-full chip coming
                    bank.quantize_fallbacks[bucket.label] = bucket.quantize_error
                    if bank._m_quant_fallback is not None:
                        bank._m_quant_fallback.labels(bucket.label).inc()
            except Exception as exc:
                logger.error(
                    "Bucket %s finalize FAILED (%d member(s) fall back to "
                    "the per-model path): %s",
                    bucket.label, len(bucket.names), exc, exc_info=True,
                )
                del bank._buckets[key]
                reason = f"bucket finalize failed: {type(exc).__name__}: {exc}"
                bank.finalize_failures[bucket.label] = reason
                for name in bucket.names:
                    bank._index.pop(name, None)
                    bank._tags.pop(name, None)
                    bank.fallback[name] = reason
        if bank._index:
            logger.info(
                "Model bank: %d models in %d bucket(s)%s",
                len(bank._index),
                len(bank._buckets),
                ""
                if bank.mesh is None
                else f", sharded over {bank.mesh.devices.size} device(s)",
            )
        # coverage is an operator signal: at 10k models a DEBUG line per
        # fallback is invisible — surface the aggregate loudly (and per
        # model through /models; see views.list_models)
        if bank.fallback:
            logger.warning(
                "Model bank: %d/%d model(s) NOT banked (per-model scoring "
                "path): %s",
                len(bank.fallback),
                len(bank.fallback) + len(bank._index),
                ", ".join(
                    f"{n} ({r})" for n, r in sorted(bank.fallback.items())[:10]
                )
                + (" ..." if len(bank.fallback) > 10 else ""),
            )
        return bank

    def coverage(self) -> Dict[str, Any]:
        """Operator-facing bank coverage summary."""
        return {
            "banked": len(self._index),
            "fallback": dict(self.fallback),
            "n_buckets": len(self._buckets),
            # how many chips the stacked state is sharded over (1 =
            # single-device bank) — lets an operator confirm an 8-chip
            # server is actually using its slice from /models alone
            "devices": int(self.mesh.devices.size) if self.mesh is not None else 1,
            "bank_dtype": self.bank_dtype,
            "kernel": self.kernel_mode,
        }

    def capacity_stats(self) -> Dict[str, Any]:
        """Operator-facing HBM capacity summary (served in ``/stats`` as
        ``bank_capacity``; bench and the north-star check record it so
        the models-per-GB trajectory is auditable).

        ``weight_bytes`` is the stacked params' storage footprint at the
        effective dtype mix; ``fp32_bytes`` the same stack at fp32 —
        their ratio is the capacity win low-precision storage bought.
        Buckets whose quantization fell back to fp32 appear in
        ``quantize_fallbacks`` and drag the ratio toward 1."""
        total = sum(b.weight_bytes for b in self._buckets.values())
        fp32 = sum(b.weight_bytes_fp32 for b in self._buckets.values())
        by_dtype: Dict[str, int] = {}
        for b in self._buckets.values():
            d = b.effective_dtype
            by_dtype[d] = by_dtype.get(d, 0) + b.weight_bytes
        members = len(self._index)
        bpm = total / members if members else None
        return {
            "dtype": self.bank_dtype,
            "kernel": self.kernel_mode,
            "members": members,
            "weight_bytes": total,
            "weight_bytes_by_dtype": by_dtype,
            "fp32_bytes": fp32,
            "capacity_ratio": round(fp32 / total, 3) if total else None,
            "bytes_per_member": round(bpm, 1) if bpm is not None else None,
            "models_per_gb": (
                round(1024**3 / bpm, 1) if bpm else None
            ),
            "quantize_fallbacks": dict(self.quantize_fallbacks),
        }

    def flops_stats(self) -> Dict[str, Any]:
        """Static per-bucket FLOPs table (cost model's numerator feed,
        observability/cost.py): bucket label -> the analytic forward
        FLOPs per routed row computed once at finalize, plus the shape
        facts a capacity advisor needs. Finalize-failed buckets are
        absent — they never burn device time."""
        out: Dict[str, Any] = {}
        for b in self._buckets.values():
            out[b.label] = {
                "flops_per_row": float(b.flops_per_row),
                "flops_method": b.flops_method,
                "params_per_member": int(b.params_per_member),
                "members": len(b.names),
                "kind": b.kind,
                "registry_type": b.registry_type,
                "n_features": int(b.n_features),
                "lookback": int(b.lookback),
                "weight_bytes": int(b.weight_bytes),
                "effective_dtype": b.effective_dtype,
                # sequence fast-path provenance (ops/seq_scan.py):
                # which layout/kernel the compiled scoring program uses
                "seq_layout": getattr(b, "seq_layout", "legacy"),
                "seq_kernel": getattr(b, "seq_kernel", "jnp"),
            }
        return out

    def pipeline_stats(self) -> Dict[str, Any]:
        """Operator-facing pipeline/arena summary (served in ``/stats``
        as ``bank_pipeline``; bench and the north-star check snapshot it
        so the overlap trajectory is auditable)."""
        pipe = self._pipe
        wall = pipe["wall_s"]
        return {
            "inflight_window": self._inflight_window,
            "arena": self.arena.stats(),
            "overlap": {
                "calls": pipe["calls"],
                "multi_group_calls": pipe["multi_group_calls"],
                "device_busy_s": round(pipe["device_busy_s"], 6),
                "wall_s": round(wall, 6),
                "overlap_ratio": (
                    round(pipe["device_busy_s"] / wall, 4) if wall > 0 else None
                ),
            },
        }

    def placement(self) -> Dict[str, Any]:
        """The live model->shard assignment (placement control plane's
        input; served through ``GET /placement``): per bucket, the
        members in stack order — member i of a bucket lives on shard
        ``i // shard_size`` (contiguous blocks along the stacked model
        axis, ``_Bucket.finalize``). Single-device banks report one
        shard holding everything."""
        buckets = []
        for key, b in self._buckets.items():
            buckets.append(
                {
                    "bucket": b.label,
                    "key": key,
                    "n_shards": int(b.n_shards),
                    "shard_size": int(b.shard_size or len(b.names)),
                    "members": list(b.names),
                }
            )
        return {
            "bank_generation": int(self.generation),
            "devices": (
                int(self.mesh.devices.size) if self.mesh is not None else 1
            ),
            "buckets": buckets,
        }

    @staticmethod
    def _warmup_grid_env(name: str, default: Tuple[int, ...]) -> Tuple[int, ...]:
        raw = os.environ.get(name)
        if not raw:
            return default
        try:
            vals = tuple(int(v) for v in raw.split(",") if v.strip())
        except ValueError:
            logger.warning(
                "%s must be comma-separated integers, got %r; using %s",
                name, raw, default,
            )
            return default
        return vals or default

    def warmup(self, rows=None, batch_sizes=None) -> int:
        """Pre-compile each bucket's scoring program over a (B, T) shape
        grid so neither the first request NOR the first coalesced burst
        pays an XLA compile (seconds) — run at server startup, off the
        request path. Returns the number of buckets warmed.

        ``rows`` is an int or sequence of row counts (default env
        ``GORDO_WARMUP_ROWS``, else 256); ``batch_sizes`` a sequence of
        batch widths (default env ``GORDO_WARMUP_BATCHES``, else ``1``).
        Both are rounded up to the pow2 ladder score_many actually
        dispatches, and the grid is their cross product — with the
        persistent compilation cache (``GORDO_COMPILE_CACHE_DIR``) the
        grid compiles once per fleet, not once per restart."""
        if rows is None:
            row_list = self._warmup_grid_env("GORDO_WARMUP_ROWS", (256,))
        elif isinstance(rows, int):
            row_list = (rows,)
        else:
            row_list = tuple(rows)
        if batch_sizes is None:
            batch_sizes = self._warmup_grid_env("GORDO_WARMUP_BATCHES", (1,))
        batches = sorted({_next_pow2(max(1, int(b))) for b in batch_sizes})
        warmed = 0
        total_shapes = 0
        for bucket in self._buckets.values():
            shapes = sorted(
                {
                    (
                        # EXACTLY score_many's T computation (clamp to
                        # max_rows, then floor at the warm-up window) —
                        # warming any other shape leaves the dispatched
                        # one cold and compiles a dead program
                        max(
                            min(
                                _next_pow2(max(1, int(r))),
                                _prev_pow2(self.max_rows),
                            ),
                            _next_pow2(bucket.offset + 1),
                        ),
                        B,
                    )
                    for r in row_list
                    for B in batches
                }
            )
            try:
                for T, B in shapes:
                    if self.mesh is None:
                        X = np.zeros((B, T, bucket.n_features), np.float32)
                        bucket.score_batch(np.zeros((B,), np.int32), X, X)
                    else:
                        D = bucket.n_shards
                        X = np.zeros((D, B, T, bucket.n_features), np.float32)
                        bucket.score_batch_sharded(
                            np.zeros((D, B), np.int32), X, X
                        )
                warmed += 1
                total_shapes += len(shapes)
            except Exception:
                logger.warning(
                    "bank warmup failed for bucket %s/%s",
                    bucket.registry_type, bucket.kind, exc_info=True,
                )
        if warmed:
            logger.info(
                "Model bank warmed: %d bucket(s) pre-compiled over %d "
                "(rows, batch) shape(s)",
                warmed, total_shapes,
            )
        return warmed

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def __len__(self) -> int:
        return len(self._index)

    @property
    def n_buckets(self) -> int:
        return len(self._buckets)

    # --------------------------- scoring ------------------------------ #

    def score(
        self,
        name: str,
        X: np.ndarray,
        y: Optional[np.ndarray] = None,
        trace=None,
    ) -> ScoreResult:
        """Score one request (convenience wrapper over ``score_many``)."""
        return self.score_many(
            [(name, X, y)], traces=None if trace is None else [trace]
        )[0]

    def score_many(
        self,
        requests: Sequence[Tuple[str, np.ndarray, Optional[np.ndarray]]],
        traces: Optional[Sequence[Any]] = None,
        deadline: Optional[Deadline] = None,
        return_exceptions: bool = False,
    ) -> List[Any]:
        """Score a heterogeneous batch of (name, X, y) requests.

        Requests are grouped by bucket and each group runs through a
        three-stage software pipeline — :meth:`_host_prep` (coalesce +
        pad into arena scratch buffers), :meth:`_dispatch` (the XLA call,
        returned WITHOUT fetching so JAX async dispatch keeps the device
        queue full), :meth:`_postprocess` (fence + fetch + reassemble) —
        with up to ``GORDO_BANK_INFLIGHT`` (default 2) groups in flight:
        while group k executes on the device, group k+1 is padded on the
        host and group k-1's outputs are fetched. Heterogeneous
        multi-bucket batches no longer serialize host and device work;
        outputs are bitwise identical to the serial (window=1) order.

        ``deadline`` (optional, the batch's earliest
        :class:`~gordo_components_tpu.resilience.deadline.Deadline`) is
        checked BETWEEN bucket-group dispatches: a multi-group call whose
        budget runs out mid-way raises :class:`DeadlineExceeded` instead
        of burning device time on groups nobody is still waiting for.
        The caller (the batching engine) resolves each pending against
        its own deadline — expired ones 504, the rest re-score
        individually.

        ``traces`` (optional, request-aligned; entries may be None) are
        :class:`~gordo_components_tpu.observability.tracing.Trace`
        objects to record the hot-path stage spans into — ``coalesce``,
        ``pad``, ``device_execute`` (dispatch -> fenced-ready, the
        group's device window), ``postprocess``, plus one
        ``pipeline_overlap`` span per multi-group call carrying the
        measured overlap ratio. The stage-timing path is skipped when no
        request in a group is traced (the near-free-when-disabled
        contract; see the tracing hot-loop overhead guard).

        ``return_exceptions`` (the batching engine's mode): instead of
        raising on the first failure, a failed bucket group's requests
        get their exception as their result-list entry while every other
        group still returns real :class:`ScoreResult` objects — one
        poisoned group no longer discards a whole coalesced batch.
        """
        results: List[Any] = [None] * len(requests)
        errors: Dict[int, Exception] = {}
        by_bucket: Dict[str, List[int]] = {}
        for ri, (name, X, _y) in enumerate(requests):
            entry = self._index.get(name)
            if entry is None:
                exc = KeyError(f"Model {name!r} not in bank")
                if not return_exceptions:
                    raise exc
                errors[ri] = exc
                continue
            by_bucket.setdefault(entry[0], []).append(ri)

        groups = list(by_bucket.items())
        n_groups = len(groups)
        window = self._inflight_window
        inflight: "deque[_GroupRun]" = deque()
        t_call = time.monotonic()
        device_busy = 0.0
        last_ready = t_call

        def poll_inflight() -> None:
            # stamp device completions at host stage boundaries: without
            # this, a group's device window would only close at its
            # fence — absorbing any host work run in between and pinning
            # the measured overlap ratio near 1.0 no matter how long the
            # device actually idled
            if inflight:
                now = time.monotonic()
                for r in inflight:
                    r.poll_ready(now)

        def finish(run: _GroupRun) -> None:
            nonlocal device_busy, last_ready
            poll_inflight()
            ok = True
            try:
                self._postprocess(run, requests, results, traces)
            except Exception as exc:
                ok = False
                if not return_exceptions:
                    raise
                for ri in run.req_ids:
                    errors[ri] = exc
            # window end: the earliest OBSERVED completion — the polled
            # stamp when the device finished during host work, the fence
            # time when the host genuinely waited (then the fence end IS
            # the completion). Windows never overlap: queue wait behind
            # the previous group's execution must not be counted twice.
            t_done = run.t_device_done or run.t_ready
            window = max(0.0, t_done - max(run.t_dispatch, last_ready))
            device_busy += window
            last_ready = max(last_ready, t_done)
            if self.ledger is not None:
                self._account_group(run, results, window, ok)

        try:
            for gi, (key, req_ids) in enumerate(groups):
                if deadline is not None and deadline.expired():
                    # stop between group dispatches: the budget the engine
                    # admitted this batch under has run out, and the next
                    # XLA call would compute answers nobody reads
                    exc = DeadlineExceeded(
                        f"batch deadline expired before all {n_groups} "
                        f"bucket group(s) dispatched "
                        f"(budget {deadline.budget_s * 1e3:.0f}ms)"
                    )
                    if not return_exceptions:
                        raise exc
                    for _key, rids in groups[gi:]:
                        for ri in rids:
                            errors[ri] = exc
                    break
                run = None
                try:
                    run = self._host_prep(key, req_ids, requests, traces)
                    self._dispatch(run)
                except Exception as exc:
                    # the failed group's own buffers (host_prep cleans up
                    # after itself, but a dispatch failure leaves them on
                    # the run) go back to the arena either way
                    if run is not None:
                        run.release(self.arena)
                    if not return_exceptions:
                        raise
                    for ri in req_ids:
                        errors[ri] = exc
                    continue
                inflight.append(run)
                self._inflight_now = len(inflight)
                poll_inflight()  # completions during this group's prep
                if len(inflight) >= window:
                    finish(inflight.popleft())
                    self._inflight_now = len(inflight)
            while inflight:
                finish(inflight.popleft())
                self._inflight_now = len(inflight)
        except BaseException:
            # an aborted call must not leak arena buffers or abandon
            # device work mid-flight: fence and release every in-flight
            # group before the exception propagates, so no buffer is
            # ever handed to a later request while still referenced
            for run in inflight:
                with contextlib.suppress(Exception):
                    jax.block_until_ready(run.out)
                run.release(self.arena)
            self._inflight_now = 0
            raise
        self._inflight_now = 0

        self._pipe["calls"] += 1
        if n_groups > 1:
            t_end = time.monotonic()
            wall = t_end - t_call
            self._pipe["multi_group_calls"] += 1
            self._pipe["wall_s"] += wall
            self._pipe["device_busy_s"] += device_busy
            if traces is not None:
                ratio = device_busy / wall if wall > 0 else 0.0
                for ri, tr in enumerate(traces):
                    # only requests that actually rode the pipeline: a
                    # never-grouped (unknown-model) or deadline-dropped
                    # request must not show device work in its trace
                    if tr is None or ri in errors:
                        continue
                    tr.add_span(
                        "pipeline_overlap", t_call, t_end,
                        groups=n_groups, window=window,
                        device_busy_ms=round(device_busy * 1e3, 3),
                        overlap_ratio=round(ratio, 4),
                    )
        for ri, exc in errors.items():
            results[ri] = exc
        return results

    def _host_prep(
        self,
        key: str,
        req_ids: List[int],
        requests: Sequence[Tuple[str, np.ndarray, Optional[np.ndarray]]],
        traces: Optional[Sequence[Any]],
    ) -> _GroupRun:
        """Pipeline stage 1 — coalesce + pad (pure host work).

        Validates the group's requests, chunks long ones (sequence chunks
        OVERLAP by the warm-up so no output rows are lost at chunk
        boundaries), and assembles the pow2-padded batch arrays in arena
        scratch buffers, zeroing only the pad tail of reused buffers."""
        bucket = self._buckets[key]
        run = _GroupRun()
        run.bucket = bucket
        run.req_ids = req_ids
        group_traces = None
        if traces is not None:
            group_traces = [
                t for t in (traces[ri] for ri in req_ids) if t is not None
            ] or None
        run.group_traces = group_traces
        # stage timestamps serve BOTH tracing and goodput accounting;
        # with neither attached they stay 0.0 and cost nothing
        timed = group_traces is not None or self.ledger is not None
        run.t_group = time.monotonic() if timed else 0.0
        F = bucket.n_features
        off = bucket.offset
        run.off = off
        rows = [np.asarray(requests[ri][1], np.float32) for ri in req_ids]
        mrows = self.model_rows
        heat = self.heat
        # the heat accountant's hot-path mailbox, cached once per group
        # (observability/heat.py): one dict get+set per request below,
        # decay math amortized into the accountant's sampling cadence
        pend = heat.pending if heat is not None else None
        for ri, X in zip(req_ids, rows):
            if X.ndim != 2 or X.shape[1] != F:
                raise ValueError(
                    f"Request for {requests[ri][0]!r}: expected (rows, {F}), "
                    f"got {X.shape}"
                )
            if X.shape[0] == 0:
                raise ValueError(f"Request for {requests[ri][0]!r}: empty input")
            if X.shape[0] <= off:
                raise ValueError(
                    f"Request for {requests[ri][0]!r}: need more than "
                    f"{off} rows (sequence warm-up), got {X.shape[0]}"
                )
            if mrows is not None:
                # the planner's per-model load signal (rebalancing acts
                # on rows, the unit the shard counters already speak)
                name = requests[ri][0]
                mrows[name] = mrows.get(name, 0) + X.shape[0]
                if pend is not None:
                    pend[name] = pend.get(name, 0.0) + X.shape[0]
            elif pend is not None:
                name = requests[ri][0]
                pend[name] = pend.get(name, 0.0) + X.shape[0]
        # rows-per-call stays a power of two and never exceeds max_rows
        # (but must always cover at least one window + one output row)
        T = min(
            _next_pow2(max(x.shape[0] for x in rows)), _prev_pow2(self.max_rows)
        )
        T = max(T, _next_pow2(off + 1))
        step = T - off
        chunks: List[Tuple[int, np.ndarray, np.ndarray]] = []
        # per-request reassembly plan, built once here instead of the
        # post-hoc per_req/valid dict churn the reassembly loop used to
        # re-derive per call (each chunk yields rows [start+off, start+T))
        req_plans: List[Tuple[int, np.ndarray, List[int], List[int], int]] = []
        for ri, X in zip(req_ids, rows):
            yv = requests[ri][2]
            if yv is None:
                Y = X
            else:
                Y = np.asarray(yv, np.float32)
                if Y.shape != X.shape:
                    raise ValueError(
                        f"Request for {requests[ri][0]!r}: y shape {Y.shape} "
                        f"must match X shape {X.shape}"
                    )
            cis: List[int] = []
            valids: List[int] = []
            for start in range(0, X.shape[0] - off, step):
                xc = X[start : start + T]
                cis.append(len(chunks))
                valids.append(xc.shape[0] - off)
                chunks.append((ri, xc, Y[start : start + T]))
            # the already-converted array rides into
            # ScoreResult.model_input, so the response path stops paying
            # a second np.asarray(X, float32) per request
            req_plans.append((ri, X, cis, valids, X.shape[0] - off))
        run.req_plans = req_plans
        run.n_chunks = len(chunks)
        run.t_chunks = time.monotonic() if timed else 0.0
        if self._m_shard_rows is not None:
            # per-bucket coalescing visibility: dispatches, request
            # fan-in, and the coalesced batch-size distribution
            blabel = bucket.label
            self._m_bucket_calls.labels(blabel).inc()
            self._m_bucket_reqs.labels(blabel).inc(len(req_ids))
            self._m_bucket_batch.labels(blabel).record(float(len(chunks)))
        try:
            if self.mesh is None:
                B = _next_pow2(len(chunks))
                Xb, x_clean = self.arena.acquire((B, T, F))
                run._bufs = (Xb,)  # attached NOW: a failed second acquire
                # must not strand the first buffer outside the arena
                Yb, y_clean = self.arena.acquire((B, T, F))
                run._bufs = (Xb, Yb)
                idx = np.zeros((B,), np.int32)
                # slots[ci]: where chunk ci landed in the batched output —
                # a flat index here, a (device, local-slot) pair under
                # mesh routing
                slots: List[Any] = list(range(len(chunks)))
                routed0 = 0
                for ci, (ri, xc, yc) in enumerate(chunks):
                    n = xc.shape[0]
                    Xb[ci, :n] = xc
                    Yb[ci, :n] = yc
                    if n < T:
                        if not x_clean:
                            Xb[ci, n:] = 0.0
                        if not y_clean:
                            Yb[ci, n:] = 0.0
                    idx[ci] = self._index[requests[ri][0]][1]
                    routed0 += n
                if not x_clean:
                    Xb[len(chunks):] = 0.0
                if not y_clean:
                    Yb[len(chunks):] = 0.0
                if self._m_shard_rows is not None:
                    self._m_shard_rows.labels("0").inc(routed0)
                    self._m_shard_pad.labels("0").inc(B * T - routed0)
                    self._m_shard_reqs.labels("0").inc(len(chunks))
                run.routed_rows = routed0
                run.total_rows = B * T
                run.shard_rows = (("0", routed0, B * T - routed0),)
                run.score_fn = bucket.score_batch
            else:
                # route each chunk to the shard owning its model: the
                # stacked leading axis is split into n_shards contiguous
                # blocks of shard_size (parallel/mesh.shard_model_axis)
                D, shard = bucket.n_shards, bucket.shard_size
                per_dev: List[List[int]] = [[] for _ in range(D)]
                for ci, (ri, _xc, _yc) in enumerate(chunks):
                    per_dev[self._index[requests[ri][0]][1] // shard].append(ci)
                Bl = _next_pow2(max(1, max(len(c) for c in per_dev)))
                Xb, x_clean = self.arena.acquire((D, Bl, T, F))
                run._bufs = (Xb,)
                Yb, y_clean = self.arena.acquire((D, Bl, T, F))
                run._bufs = (Xb, Yb)
                idx = np.zeros((D, Bl), np.int32)
                slots = [None] * len(chunks)
                shard_rows: List[Tuple[str, int, int]] = []
                for d, dev_cis in enumerate(per_dev):
                    routed_d = 0
                    for j, ci in enumerate(dev_cis):
                        ri, xc, yc = chunks[ci]
                        n = xc.shape[0]
                        Xb[d, j, :n] = xc
                        Yb[d, j, :n] = yc
                        if n < T:
                            if not x_clean:
                                Xb[d, j, n:] = 0.0
                            if not y_clean:
                                Yb[d, j, n:] = 0.0
                        idx[d, j] = self._index[requests[ri][0]][1] - d * shard
                        slots[ci] = (d, j)
                        routed_d += n
                    if not x_clean:
                        Xb[d, len(dev_cis):] = 0.0
                    if not y_clean:
                        Yb[d, len(dev_cis):] = 0.0
                    if self._m_shard_rows is not None:
                        # every device executes Bl * T rows regardless of
                        # how many are real: the routed/padded split is the
                        # per-shard skew an operator needs to SEE (a hot
                        # model concentrates routed rows on one shard while
                        # the rest burn the same FLOPs on padding)
                        sl = str(d)
                        self._m_shard_rows.labels(sl).inc(routed_d)
                        self._m_shard_pad.labels(sl).inc(Bl * T - routed_d)
                        self._m_shard_reqs.labels(sl).inc(len(dev_cis))
                    shard_rows.append((str(d), routed_d, Bl * T - routed_d))
                run.routed_rows = sum(r for _s, r, _p in shard_rows)
                run.total_rows = D * Bl * T
                run.shard_rows = tuple(shard_rows)
                run.score_fn = bucket.score_batch_sharded
        except BaseException:
            run.release(self.arena)
            raise
        run.Xb, run.Yb, run.idx = Xb, Yb, idx
        run.slots = slots
        run.t_pad = time.monotonic() if timed else 0.0
        return run

    def _dispatch(self, run: _GroupRun) -> None:
        """Pipeline stage 2 — async device dispatch.

        The XLA call returns device arrays WITHOUT fetching them (JAX
        async dispatch), so the host is free to pad the next group and
        fetch the previous one while this group executes; the device
        window closes at :meth:`_postprocess`'s fence."""
        _FP_SCORE.fire()
        run.t_dispatch = time.monotonic()
        prof_root = (
            os.environ.get("GORDO_PROFILE_DIR") if run.group_traces else None
        )
        if prof_root:
            # JAX profiler capture of exactly this dispatch
            # (utils/profiling.maybe_profile): the profiler trace
            # directory is named by the request's trace id, so the span
            # tree and the op-level timeline share one identity — the
            # span's ``profile`` attribute links them. The capture must
            # SEE the execution, so this opt-in debugging path fences
            # inside the profile context, serializing only this group.
            from gordo_components_tpu.utils.profiling import maybe_profile

            prof_name = f"serve-{run.group_traces[0].trace_id}"
            run.profile_dir = os.path.join(prof_root, prof_name)
            with maybe_profile(prof_name):
                run.out = run.score_fn(run.idx, run.Xb, run.Yb)
                jax.block_until_ready(run.out)
            run.t_ready = run.t_device_done = time.monotonic()
        else:
            run.out = run.score_fn(run.idx, run.Xb, run.Yb)

    def _postprocess(
        self,
        run: _GroupRun,
        requests: Sequence[Tuple[str, np.ndarray, Optional[np.ndarray]]],
        results: List[Any],
        traces: Optional[Sequence[Any]],
    ) -> None:
        """Pipeline stage 3 — fence, fetch, reassemble, release."""
        try:
            if not run.t_ready:
                try:
                    # fence: this group's device window ends HERE (a
                    # device-side error surfaces here too, after the
                    # timestamp, so overlap accounting stays sane)
                    jax.block_until_ready(run.out)
                finally:
                    run.t_ready = time.monotonic()
            # one transfer for all five outputs (device_get batches the
            # D2H copies) instead of five blocking np.asarray round-trips
            outs = jax.device_get(run.out)
            slots = run.slots
            for ri, X_conv, cis, valids, n_out in run.req_plans:
                if len(cis) == 1:
                    vals = _slice_single(outs, slots[cis[0]], n_out)
                else:
                    vals = _concat_chunks(outs, slots, cis, valids, n_out)
                results[ri] = ScoreResult(
                    tags=self._tags[requests[ri][0]],
                    model_input=X_conv,
                    model_output=vals[0],
                    diff=vals[1],
                    scaled=vals[2],
                    total_unscaled=vals[3],
                    total_scaled=vals[4],
                    offset=run.off,
                )
            if run.group_traces or self.ledger is not None:
                run.t_post = time.monotonic()
            if run.group_traces:
                # the stage boundaries are per coalesced GROUP: every
                # traced request in it gets the same span timestamps —
                # per-request attribution of the shared batch's cost,
                # which is exactly what coalescing makes invisible in a
                # plain latency histogram
                t_done = run.t_post
                blabel = run.bucket.label
                for ri in run.req_ids:
                    tr = traces[ri]  # type: ignore[index]
                    if tr is None:
                        continue
                    tr.add_span(
                        "coalesce", run.t_group, run.t_chunks,
                        bucket=blabel, requests=len(run.req_ids),
                        chunks=run.n_chunks,
                    )
                    tr.add_span("pad", run.t_chunks, run.t_pad)
                    exec_attrs: Dict[str, Any] = {"bucket": blabel}
                    if run.profile_dir is not None:
                        exec_attrs["profile"] = run.profile_dir
                    tr.add_span(
                        "device_execute", run.t_dispatch, run.t_ready,
                        **exec_attrs,
                    )
                    tr.add_span("postprocess", run.t_ready, t_done)
        finally:
            run.release(self.arena)

    def _account_group(
        self, run: _GroupRun, results: List[Any], window_s: float, ok: bool
    ) -> None:
        """Goodput accounting for one finished group (executor thread;
        observability/goodput.py). The group's device window splits by
        real-vs-pad dispatched rows: the padded share is waste the
        ledger books directly, the useful share is apportioned to the
        group's requests by their row counts (``ScoreResult.device_s``)
        so the HTTP layer can commit it as goodput or waste once each
        request's outcome is known. A failed group's useful share is
        wasted outright — the device computed answers nobody received."""
        led = self.ledger
        total = run.total_rows
        pad_frac = (1.0 - run.routed_rows / total) if total else 0.0
        padded_s = window_s * pad_frac
        useful_s = window_s - padded_s
        led.account_group(
            bucket=run.bucket.label,
            window_s=window_s,
            useful_s=useful_s,
            padded_s=padded_s,
            ok=ok,
            coalesce_s=(
                max(0.0, run.t_chunks - run.t_group) if run.t_group else 0.0
            ),
            pad_s=max(0.0, run.t_pad - run.t_chunks) if run.t_chunks else 0.0,
            postprocess_s=(
                max(0.0, run.t_post - run.t_ready) if run.t_post else 0.0
            ),
            shard_rows=run.shard_rows,
        )
        if ok and useful_s > 0.0:
            req_rows = sum(plan[1].shape[0] for plan in run.req_plans)
            if req_rows:
                per_row = useful_s / req_rows
                for ri, X_conv, _cis, _valids, _n_out in run.req_plans:
                    r = results[ri]
                    if isinstance(r, ScoreResult):
                        r.device_s = per_row * X_conv.shape[0]


# --------------------------------------------------------------------- #
# continuous batching
# --------------------------------------------------------------------- #


@dataclass
class _Pending:
    name: str
    X: np.ndarray
    y: Optional[np.ndarray]
    future: asyncio.Future
    enqueued: float  # monotonic seconds at score() submission (required:
    # a forgotten timestamp would record ~uptime into the histograms)
    # request-id propagated from the HTTP layer (client header or
    # server-generated): failures inside the coalesced batch stay
    # traceable to the access-log line that admitted the request
    request_id: Optional[str] = None
    # request-scoped Trace (observability/tracing.py) riding through the
    # queue: the engine records queue_wait at dispatch and the bank
    # records the batch stage spans into it; None when tracing is off
    trace: Optional[Any] = None
    # per-request time budget (resilience/deadline.py): an entry whose
    # deadline passes while it waits in the queue is dropped BEFORE
    # device dispatch and resolved with DeadlineExceeded (HTTP 504) —
    # saturated replicas must spend TPU time only on answers someone is
    # still waiting for; None = no budget, never expires
    deadline: Optional[Deadline] = None
    # QoS identity stamped at admission (qos/classify.py): the fair
    # queue dequeues by qos_class, and sheds/deadline-expiries attribute
    # to the right (tenant, class) in /stats and the per-class ledger
    # cells even when the drop happens long after the HTTP layer let go
    tenant: str = "default"
    qos_class: str = "interactive"


class EngineOverloaded(Exception):
    """The engine's queue is full: offered load exceeds capacity.

    Carries ``retry_after_s`` — a drain-time estimate the HTTP layer
    surfaces as ``Retry-After`` on its 429 (views.py)."""

    def __init__(self, depth: int, retry_after_s: float):
        self.depth = depth
        self.retry_after_s = retry_after_s
        super().__init__(
            f"scoring queue full ({depth} pending); retry in ~{retry_after_s:.1f}s"
        )


class BatchingEngine:
    """Coalesce concurrent scoring requests into batched bank calls.

    Requests arriving while a batch is in flight (or within ``flush_ms`` of
    the first waiter) are scored together: one XLA dispatch for up to
    ``max_batch`` models' requests. XLA execution runs in a thread-pool
    executor so the event loop keeps accepting requests — continuous
    batching in the LLM-serving sense, applied to anomaly scoring.

    Backpressure: the queue is bounded at ``max_queue`` (default
    ``8 * max_batch``). When it is full, ``score()`` raises
    :class:`EngineOverloaded` immediately instead of enqueueing — offered
    load past capacity sheds with a 429 at the HTTP layer rather than
    growing an unbounded queue whose every waiter times out. Sheds are
    counted in ``stats["shed"]``.
    """

    def __init__(
        self,
        bank: ModelBank,
        max_batch: int = 64,
        flush_ms: float = 2.0,
        max_queue: Optional[int] = None,
        registry=None,
        dispatch_lock=None,
        class_weights=None,
    ):
        self.bank = bank
        # multi-worker serving (server/workers.py): each worker loop
        # runs its OWN engine over the ONE shared bank, and this shared
        # threading.Lock serializes their bank calls on the executor
        # threads — the device was never going to run two batches at
        # once anyway, and per-worker engines mean a request never pays
        # a cross-loop hop (measured at multiple GIL-switch intervals
        # per request) while XLA's GIL release lets the other workers
        # parse/coalesce DURING a dispatch. None (the default) is the
        # classic single-engine layout with zero added work.
        self.dispatch_lock = dispatch_lock
        self.max_batch = int(max_batch)
        self.flush_s = float(flush_ms) / 1e3
        if max_queue is None:
            max_queue = 8 * self.max_batch
        if int(max_queue) <= 0:
            raise ValueError(f"max_queue must be positive, got {max_queue!r}")
        self.max_queue = int(max_queue)
        # weighted-fair queue (qos/fair.py): duck-compatible with the
        # asyncio.Queue it replaced — per-class virtual-time dequeue so
        # a batch-class flood cannot starve interactive traffic, and
        # deadline-ordered pops inside each class. With every request in
        # the default class (no QoS config) this degenerates to FIFO.
        from gordo_components_tpu.qos.fair import WeightedFairQueue, parse_weights

        if class_weights is None:
            class_weights = parse_weights()
        self._queue: "WeightedFairQueue" = WeightedFairQueue(class_weights)
        self._task: Optional[asyncio.Task] = None
        # the loop that owns the queue + consumer task, captured at
        # start(): every engine-internal future/queue op must happen on
        # THIS loop. Other loops (multi-worker serving, server/workers.py)
        # and plain threads (the shm transport) enter through submit() /
        # score_blocking(), which hop here thread-safely.
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        # group-isolation capability of the current bank (score_many's
        # ``return_exceptions``), probed once per bank object: proxies
        # and stubs with the minimal score_many(requests) signature keep
        # the legacy whole-batch retry path. Held as a weakref: a strong
        # reference would pin a /reload-replaced bank's HBM-resident
        # params (and arena pool) until the next batch re-probes.
        self._partial_bank: Any = None
        self._partial_ok = False
        self.stats = {
            "requests": 0,
            "batches": 0,
            "max_batch_seen": 0,
            "shed": 0,
            "deadline_expired": 0,
        }
        # per-class attribution of the same events (ISSUE 19 satellite:
        # sheds and deadline-expiry drops must name the class/tenant that
        # ate them, retroactively visible in /stats and /metrics)
        from gordo_components_tpu.qos.classify import CLASSES

        self.class_stats = {
            c: {"requests": 0, "shed": 0, "deadline_expired": 0}
            for c in CLASSES
        }
        # the flush_ms coalescing window trades latency for throughput;
        # these histograms quantify that trade (VERDICT r3 next #4):
        # queue_wait = submit -> batch dispatch, service = submit -> result
        from gordo_components_tpu.server.stats import LatencyHistogram

        # registry default: inherit the bank's (already resolved there; a
        # bank built with registry=False propagates "uninstrumented").
        # The engine's own counters stay in the plain ``stats`` dict and
        # are exposed through a read-at-render-time collector, so the
        # scrape endpoint and /stats read the SAME integers — no mirrored
        # counters, no drift, zero extra work on the hot loop.
        if registry is None:
            registry = getattr(bank, "registry", None)
        elif registry is False:
            registry = None
        self.registry = registry
        if registry is not None:
            self.queue_wait = registry.histogram(
                "gordo_engine_queue_wait_seconds",
                "Submit -> batch-dispatch wait (what flush_ms coalescing costs)",
            ).labels()
            self.service = registry.histogram(
                "gordo_engine_service_seconds",
                "Submit -> result service time through the batching engine",
            ).labels()
            # weakref: the collector lives as long as the registry (which
            # may be process-global); it must not pin a discarded engine —
            # and, through engine.bank, a whole bank's device state
            ref = weakref.ref(self)

            def collect():
                engine = ref()
                return engine._collect_metrics() if engine is not None else ()

            registry.collector(collect, key="bank_engine")
        else:
            self.queue_wait = LatencyHistogram()
            self.service = LatencyHistogram()

    @staticmethod
    def _resolve(fut, result=None, exc=None) -> None:
        """Resolve a pending's future, tolerating a concurrent
        cancellation. Cross-loop/thread submissions carry
        ``concurrent.futures.Future``s whose ``cancel()`` runs on the
        CALLER's thread — a ``done()`` pre-check on the engine loop is
        a TOCTOU, and an unguarded ``set_result`` racing it would raise
        ``InvalidStateError`` out of ``_run_loop`` and kill the engine
        task (every later request would then hang). A cancelled caller
        no longer wants the result; dropping it is the correct
        outcome."""
        try:
            if fut.done():
                return
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)
        except (ConcurrentInvalidState, asyncio.InvalidStateError):
            pass

    def _bank_call(self, fn, *args, **kwargs):
        """Run a bank entrypoint (executor thread), serialized by the
        shared dispatch lock when several worker engines front one
        bank."""
        if self.dispatch_lock is None:
            return fn(*args, **kwargs)
        with self.dispatch_lock:
            return fn(*args, **kwargs)

    def _collect_metrics(self):
        """Read-through exposition of the engine's counters/queue state."""
        s = self.stats
        yield (
            "gordo_engine_requests_total", "counter",
            "Requests accepted by the batching engine", {}, s["requests"],
        )
        yield (
            "gordo_engine_batches_total", "counter",
            "Coalesced batches dispatched", {}, s["batches"],
        )
        yield (
            "gordo_engine_shed_total", "counter",
            "Requests shed with 429 because the queue was full", {}, s["shed"],
        )
        yield (
            "gordo_engine_deadline_expired_total", "counter",
            "Requests whose deadline expired before device dispatch "
            "(dropped from the batch and answered 504)", {},
            s["deadline_expired"],
        )
        yield (
            "gordo_engine_max_batch_seen", "gauge",
            "Largest coalesced batch observed", {}, s["max_batch_seen"],
        )
        yield (
            "gordo_engine_queue_depth", "gauge",
            "Live scoring-queue depth", {}, self._queue.qsize(),
        )
        yield (
            "gordo_engine_max_queue", "gauge",
            "Queue bound before requests shed", {}, self.max_queue,
        )
        # per-class attribution (ISSUE 19): separate families rather than
        # extra labels on the aggregates above, so existing dashboards'
        # unlabeled series stay byte-identical
        depths = self._queue.depths() if hasattr(self._queue, "depths") else {}
        for cls, cs in self.class_stats.items():
            yield (
                "gordo_engine_class_requests_total", "counter",
                "Requests dispatched by the engine, by priority class",
                {"class": cls}, cs["requests"],
            )
            yield (
                "gordo_engine_class_shed_total", "counter",
                "Full-queue sheds by priority class",
                {"class": cls}, cs["shed"],
            )
            yield (
                "gordo_engine_class_deadline_expired_total", "counter",
                "Deadline-expiry drops by priority class",
                {"class": cls}, cs["deadline_expired"],
            )
            yield (
                "gordo_engine_class_queue_depth", "gauge",
                "Live scoring-queue depth by priority class",
                {"class": cls}, depths.get(cls, 0),
            )

    def qos_snapshot(self) -> dict:
        """Engine-side half of GET /qos: fair-queue state + per-class
        counters (read-through, same dicts the metrics render), plus
        each banked target's feature width — the promotion gate's flood
        driver needs a VALID body shape (a wrong-width flood would end
        as model errors and could trip the quarantine breaker on the
        very canary being gated)."""
        queue = (
            self._queue.snapshot() if hasattr(self._queue, "snapshot") else {}
        )
        widths: Dict[str, int] = {}
        bank = self.bank
        index = getattr(bank, "_index", None)
        buckets = getattr(bank, "_buckets", None)
        if index and buckets:
            for name, (bucket_key, _i) in index.items():
                bucket = buckets.get(bucket_key)
                if bucket is not None:
                    widths[name] = int(bucket.n_features)
        return {
            "queue": queue,
            "max_queue": self.max_queue,
            "classes": {c: dict(cs) for c, cs in self.class_stats.items()},
            "feature_widths": widths,
        }

    def start(self) -> None:
        if self._task is None:
            self._loop = asyncio.get_running_loop()
            self._task = self._loop.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
            self._loop = None

    async def submit(
        self,
        name: str,
        X: np.ndarray,
        y: Optional[np.ndarray] = None,
        request_id: Optional[str] = None,
        trace=None,
        deadline: Optional[Deadline] = None,
        tenant: str = "default",
        qos_class: str = "interactive",
    ) -> ScoreResult:
        """:meth:`score` from WHICHEVER event loop is running.

        The engine's queue belongs to the loop that called :meth:`start`
        (the primary serving loop). A multi-worker server
        (server/workers.py) parses requests on N other loops; their
        scoring hops here with ONE ``call_soon_threadsafe`` enqueue of a
        thread-safe ``concurrent.futures.Future``-backed pending — NOT a
        scheduled coroutine per request, whose wake-up jitter was
        measured to spread arrivals across flush windows and collapse
        the coalesced batch size (the whole point of the engine).
        Admission checks (expiry, shed) run caller-side against an
        approximate queue depth; their counters bump on the engine loop.
        Same-loop callers (workers=1, the default) take the direct
        path: one loop identity check, nothing else.
        """
        # local capture: stop() nulls self._loop from another thread —
        # the check and every use below must see ONE value
        loop = self._loop
        if loop is None or asyncio.get_running_loop() is loop:
            return await self.score(
                name, X, y, request_id=request_id, trace=trace,
                deadline=deadline, tenant=tenant, qos_class=qos_class,
            )
        _FP_ENGINE_QUEUE.fire()
        if deadline is not None and deadline.expired():
            self._bump_threadsafe("deadline_expired", qos_class)
            raise DeadlineExceeded(
                f"deadline expired before admission (rid={request_id}, "
                f"budget {deadline.budget_s * 1e3:.0f}ms)"
            )
        depth = self._queue.qsize()  # racy read: shed is a heuristic gate
        if depth >= self.max_queue:
            self._bump_threadsafe("shed", qos_class)
            raise EngineOverloaded(depth, self.drain_estimate(depth))
        fut: Any = ConcurrentFuture()  # thread-safe resolve from the engine loop
        pending = _Pending(
            name, X, y, fut, time.monotonic(), request_id, trace, deadline,
            tenant, qos_class,
        )
        loop.call_soon_threadsafe(self._queue.put_nowait, pending)
        # wrap_future bridges resolution (and caller-side cancellation)
        # back onto this worker's loop
        return await asyncio.wrap_future(fut)

    def _bump_threadsafe(self, key: str, qos_class: Optional[str] = None) -> None:
        """Counter increment from a foreign loop/thread, serialized onto
        the engine's loop so stats never lose increments."""
        loop = self._loop

        def bump():
            self.stats[key] = self.stats[key] + 1
            self._bump_class(qos_class, key)

        try:
            if loop is not None:
                loop.call_soon_threadsafe(bump)
        except RuntimeError:
            pass  # engine loop already closed (shutdown race): drop the count

    def _bump_class(self, qos_class: Optional[str], key: str) -> None:
        """Per-class twin of a ``stats`` bump (engine loop / same-loop
        callers only — cross-loop paths go through _bump_threadsafe)."""
        cs = self.class_stats.get(qos_class)
        if cs is not None and key in cs:
            cs[key] += 1

    def drain_estimate(self, depth: Optional[int] = None) -> float:
        """Honest Retry-After for a shed: backlog batches x per-batch
        EXECUTION time. Service p50 includes queue wait, which under
        saturation IS the backlog — subtract it or the estimate
        double-counts the queue and clients back off max_queue/max_batch
        times longer than the true drain. One estimator for every shed
        path (HTTP, cross-loop, shm) and for the admission controller."""
        if depth is None:
            depth = self._queue.qsize()
        if self.service.count:
            batch_s = max(
                self.service.percentile(0.5) - self.queue_wait.percentile(0.5),
                1e-3,
            )
        else:
            batch_s = 0.05
        return max(self.flush_s, depth / self.max_batch * batch_s)

    def score_blocking(
        self,
        name: str,
        X: np.ndarray,
        y: Optional[np.ndarray] = None,
        request_id: Optional[str] = None,
        timeout: Optional[float] = None,
        tenant: str = "default",
        qos_class: str = "interactive",
    ) -> ScoreResult:
        """:meth:`score` from a plain thread (the shared-memory transport
        server, utils/shm_ring.py): blocks the calling thread — never an
        event loop — until the engine resolves the result. Same direct
        thread-safe enqueue as cross-loop :meth:`submit`, so concurrent
        shm slots coalesce into the same batches as HTTP traffic."""
        loop = self._loop  # local: stop() nulls the attribute cross-thread
        if loop is None or not loop.is_running():
            raise RuntimeError(
                "engine loop is not running (start() the engine on a live "
                "event loop before submitting from threads)"
            )
        _FP_ENGINE_QUEUE.fire()
        depth = self._queue.qsize()
        if depth >= self.max_queue:
            self._bump_threadsafe("shed", qos_class)
            raise EngineOverloaded(depth, self.drain_estimate(depth))
        fut: Any = ConcurrentFuture()
        pending = _Pending(
            name, X, y, fut, time.monotonic(), request_id, None, None,
            tenant, qos_class,
        )
        loop.call_soon_threadsafe(self._queue.put_nowait, pending)
        try:
            return fut.result(timeout)
        except FuturesTimeoutError:
            fut.cancel()
            raise

    async def score(
        self,
        name: str,
        X: np.ndarray,
        y: Optional[np.ndarray] = None,
        request_id: Optional[str] = None,
        trace=None,
        deadline: Optional[Deadline] = None,
        tenant: str = "default",
        qos_class: str = "interactive",
    ) -> ScoreResult:
        _FP_ENGINE_QUEUE.fire()
        self.start()
        if deadline is not None and deadline.expired():
            # the budget ran out before admission (e.g. injected latency
            # upstream, or a client that stamped a near-zero budget):
            # refusing here costs nothing — queueing it would only grow
            # the backlog by work already known to be waste
            self.stats["deadline_expired"] += 1
            self._bump_class(qos_class, "deadline_expired")
            if trace is not None:
                now = time.monotonic()
                trace.add_span(
                    "deadline_expired", now, now, error=True, where="admission"
                )
            raise DeadlineExceeded(
                f"deadline expired before admission (rid={request_id}, "
                f"budget {deadline.budget_s * 1e3:.0f}ms)"
            )
        depth = self._queue.qsize()
        if depth >= self.max_queue:
            # shed NOW rather than enqueue-and-time-out: with the queue
            # this deep, a new waiter's latency is already >= the whole
            # backlog's service time, so the honest answer is "retry"
            self.stats["shed"] += 1
            self._bump_class(qos_class, "shed")
            raise EngineOverloaded(depth, self.drain_estimate(depth))
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put(
            _Pending(
                name, X, y, fut, time.monotonic(), request_id, trace,
                deadline, tenant, qos_class,
            )
        )
        return await fut

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        batch: List[_Pending] = []
        try:
            await self._run_loop(loop, batch)
        finally:
            # stop()/cancellation: resolve every future still waiting (the
            # partially-collected batch plus anything queued) so callers
            # awaiting score() don't hang forever at shutdown
            pending = list(batch)
            while True:
                try:
                    pending.append(self._queue.get_nowait())
                except asyncio.QueueEmpty:
                    break
            for p in pending:
                if not p.future.done():
                    p.future.cancel()

    async def _run_loop(self, loop, batch: List[_Pending]) -> None:
        requests = results = live = failed = None
        while True:
            batch.clear()
            # release the previous batch's references BEFORE blocking on
            # the queue: an idle engine must not pin the last requests'
            # arrays (for the shm transport those are np.frombuffer
            # views over the mapped ring) until new traffic arrives
            requests = results = live = failed = None  # noqa: F841
            first = await self._queue.get()
            batch.append(first)
            deadline = time.monotonic() + self.flush_s
            while len(batch) < self.max_batch:
                # drain whatever is already queued without arming a timer
                # per item — wait_for's per-call timer handle was real
                # heap churn in the coalesced hot loop (profiled round 5)
                try:
                    while len(batch) < self.max_batch:
                        batch.append(self._queue.get_nowait())
                    break
                except asyncio.QueueEmpty:
                    pass
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    break
                try:
                    batch.append(
                        await asyncio.wait_for(self._queue.get(), timeout=timeout)
                    )
                except asyncio.TimeoutError:
                    break
            self.stats["requests"] += len(batch)
            for p in batch:
                self._bump_class(p.qos_class, "requests")
            self.stats["batches"] += 1
            self.stats["max_batch_seen"] = max(self.stats["max_batch_seen"], len(batch))
            dispatch = time.monotonic()
            # goodput ledger, resolved through the bank so a /reload's
            # replacement bank keeps feeding the same app-level ledger
            led = getattr(self.bank, "ledger", None)
            # drop already-expired entries BEFORE device dispatch: their
            # clients stopped waiting, and under saturation executing
            # them anyway is exactly the goodput collapse the deadline
            # exists to prevent. One clock read covers the whole batch.
            live: List[_Pending] = []
            for p in batch:
                if p.deadline is not None and p.deadline.expired(dispatch):
                    self.stats["deadline_expired"] += 1
                    self._bump_class(p.qos_class, "deadline_expired")
                    self.queue_wait.record(dispatch - p.enqueued)
                    if led is not None:
                        led.record_queue_wait(dispatch - p.enqueued)
                    if p.trace is not None:
                        p.trace.add_span(
                            "deadline_expired", p.enqueued, dispatch,
                            error=True, where="queue",
                        )
                    self._resolve(
                        p.future,
                        exc=DeadlineExceeded(
                            f"deadline expired in scoring queue after "
                            f"{(dispatch - p.enqueued) * 1e3:.0f}ms wait "
                            f"(rid={p.request_id}, budget "
                            f"{p.deadline.budget_s * 1e3:.0f}ms)"
                        ),
                    )
                    self.service.record(dispatch - p.enqueued)
                else:
                    live.append(p)
            # keep the shutdown sweep's view (the caller-owned list) in
            # sync: expired entries are resolved, only live ones remain
            batch[:] = live
            if not batch:
                continue  # whole batch expired: no device dispatch at all
            traced = False
            batch_deadline: Optional[Deadline] = None
            for p in batch:
                self.queue_wait.record(dispatch - p.enqueued)
                if led is not None:
                    led.record_queue_wait(dispatch - p.enqueued)
                if p.deadline is not None and (
                    batch_deadline is None
                    or p.deadline.expires_at < batch_deadline.expires_at
                ):
                    # the EARLIEST deadline bounds the whole batch: the
                    # bank stops between bucket-group dispatches when it
                    # passes, and each pending is then re-judged against
                    # its own deadline on the retry path below
                    batch_deadline = p.deadline
                if p.trace is not None:
                    traced = True
                    # the coalescing window's per-request cost, named:
                    # submit -> batch dispatch, with the batch size the
                    # wait bought as an attribute
                    p.trace.add_span(
                        "queue_wait", p.enqueued, dispatch, batch=len(batch)
                    )
            requests = [(p.name, p.X, p.y) for p in batch]
            try:
                if self._supports_partial():
                    # group-isolated scoring: a failed bucket group (or a
                    # mid-pipeline deadline expiry) comes back as
                    # per-request exception entries while every other
                    # group's results survive — the healthy majority of
                    # a coalesced batch is never rescored
                    results = await loop.run_in_executor(
                        None,
                        functools.partial(
                            self._bank_call,
                            self.bank.score_many,
                            requests,
                            traces=[p.trace for p in batch] if traced else None,
                            deadline=batch_deadline,
                            return_exceptions=True,
                        ),
                    )
                # the traces/deadline arguments only ride along when
                # actually present: bank proxies/stubs with the minimal
                # score_many(requests) signature keep working
                elif batch_deadline is not None:
                    results = await loop.run_in_executor(
                        None,
                        functools.partial(
                            self._bank_call,
                            self.bank.score_many,
                            requests,
                            traces=[p.trace for p in batch] if traced else None,
                            deadline=batch_deadline,
                        ),
                    )
                elif traced:
                    results = await loop.run_in_executor(
                        None, self._bank_call, self.bank.score_many, requests,
                        [p.trace for p in batch],
                    )
                else:
                    results = await loop.run_in_executor(
                        None, self._bank_call, self.bank.score_many, requests
                    )
            except Exception:
                # one bad request must not poison the batch: retry each
                # request alone so errors land only on their own future.
                # A DeadlineExceeded from score_many (the batch's
                # earliest budget ran out between group dispatches)
                # lands here too: _retry_one re-judges each pending
                # against its OWN deadline — expired ones 504 without
                # another dispatch, the rest re-score individually
                for p in batch:
                    await self._retry_one(loop, p)
                continue
            done = time.monotonic()
            failed: List[_Pending] = []
            for p, r in zip(batch, results):
                if isinstance(r, Exception):
                    # only the owning group's requests walk the
                    # per-request recovery path
                    failed.append(p)
                    continue
                self._resolve(p.future, result=r)
                self.service.record(done - p.enqueued)
            # healthy futures resolve BEFORE any retry work: a failed
            # group's sequential per-request rescores must not sit in
            # front of already-computed results later in the batch order
            for p in failed:
                await self._retry_one(loop, p)

    def _supports_partial(self) -> bool:
        """Whether the current bank's ``score_many`` takes
        ``return_exceptions`` (probed once per bank object — reload swaps
        banks, and signature inspection is not hot-loop cheap)."""
        bank = self.bank
        prev = (
            self._partial_bank()
            if isinstance(self._partial_bank, weakref.ref)
            else self._partial_bank
        )
        if bank is not prev:
            try:
                self._partial_bank = weakref.ref(bank)
            except TypeError:  # non-weakref-able stub: strong ref is fine
                self._partial_bank = bank
            try:
                self._partial_ok = (
                    "return_exceptions"
                    in inspect.signature(bank.score_many).parameters
                )
            except (TypeError, ValueError):
                self._partial_ok = False
        return self._partial_ok

    async def _retry_one(self, loop, p: _Pending) -> None:
        """Per-request recovery after its batch (or just its bucket
        group) failed: re-judge the pending against its own deadline,
        then re-score it alone so an error lands only on its own
        future."""
        if p.deadline is not None and p.deadline.expired():
            self.stats["deadline_expired"] += 1
            self._bump_class(p.qos_class, "deadline_expired")
            if p.trace is not None:
                now = time.monotonic()
                p.trace.add_span(
                    "deadline_expired", p.enqueued, now,
                    error=True, where="retry",
                )
            self._resolve(
                p.future,
                exc=DeadlineExceeded(
                    f"deadline expired before retry "
                    f"(rid={p.request_id}, budget "
                    f"{p.deadline.budget_s * 1e3:.0f}ms)"
                ),
            )
            self.service.record(time.monotonic() - p.enqueued)
            return
        try:
            # carry the trace into the retry ONLY if the failed batch
            # call never recorded stage spans for this request (its
            # bucket group died before the span block) — a request whose
            # group completed before another group raised would otherwise
            # get a duplicate coalesce/pad/execute/postprocess set
            retry_trace = p.trace
            if retry_trace is not None and any(
                s.name == "device_execute" for s in retry_trace.spans
            ):
                retry_trace = None
            if retry_trace is not None:
                r = await loop.run_in_executor(
                    None, self._bank_call, self.bank.score, p.name, p.X, p.y,
                    retry_trace,
                )
            else:
                r = await loop.run_in_executor(
                    None, self._bank_call, self.bank.score, p.name, p.X, p.y
                )
        except Exception as exc:
            # rid ties this failure back to the access-log line (and
            # the client header) that admitted it
            logger.warning(
                "engine request for %r failed (rid=%s): %s",
                p.name, p.request_id, exc,
            )
            self._resolve(p.future, exc=exc)
        else:
            self._resolve(p.future, result=r)
        self.service.record(time.monotonic() - p.enqueued)
