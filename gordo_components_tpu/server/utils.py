"""Server (de)serialization helpers.

Reference parity: gordo_components/server/utils.py (unverified; SURVEY.md §2
"server") — extraction of X/y from request payloads and the
multi-level-column DataFrame ⇄ nested-dict JSON contract used by
``POST /anomaly/prediction`` and the bulk client.
"""

import io
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np
import pandas as pd


class CrossLoopLock:
    """An ``async with``-able mutex that works across EVENT LOOPS.

    ``asyncio.Lock`` binds to one loop; under the multi-worker server
    (server/workers.py) ``/reload``/``/rebalance``/``/adapt`` handlers
    can run on any worker's loop, and a loop-bound lock would either
    error or — worse — not actually exclude. This wraps a
    ``threading.Lock``: the uncontended acquire is one non-blocking
    try (the workers=1 fast path costs what ``asyncio.Lock`` did); a
    contended acquire polls with a short async sleep, which keeps the
    waiting LOOP serving traffic AND stays cancellation-safe — a
    cancelled waiter never holds the lock (an executor-thread acquire
    here would be uncancellable and could acquire after its waiter was
    gone, wedging every future rebuild). Contention is rare (reload/
    rebalance/adapt, each seconds long), so the poll adds at most one
    sleep interval to an already-slow path."""

    _POLL_S = 0.02

    def __init__(self):
        self._lock = threading.Lock()

    async def __aenter__(self):
        import asyncio

        while not self._lock.acquire(blocking=False):
            await asyncio.sleep(self._POLL_S)
        return self

    async def __aexit__(self, *exc):
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()


# guards lazy creation: with multiple worker loops the old "no await
# between check and set" single-loop argument no longer holds
_RELOAD_LOCK_INIT = threading.Lock()


def get_reload_lock(app):
    """The app's bank-rebuild serialization lock, created lazily. Every
    path that rebuilds the bank — ``/reload``, the placement controller,
    the streaming adaptation plane — MUST serialize under this one lock:
    two concurrent rebuilds would race the generation flip and double
    device memory twice over. Cross-loop by construction (see
    :class:`CrossLoopLock`) so the guarantee survives multi-worker
    serving, where the competing handlers live on different loops."""
    lock = app.get("reload_lock")
    if lock is None:
        with _RELOAD_LOCK_INIT:
            lock = app.get("reload_lock")
            if lock is None:
                lock = app["reload_lock"] = CrossLoopLock()
    return lock


def frame_to_dict(df: pd.DataFrame) -> Dict[str, Any]:
    """Multi-level (or flat) column DataFrame -> nested JSON-able dict:
    ``{"data": {top: {sub: [values]}}, "index": [...]}}``."""
    data: Dict[str, Any] = {}
    if isinstance(df.columns, pd.MultiIndex):
        for top in df.columns.get_level_values(0).unique():
            sub = df[top]
            if isinstance(sub, pd.Series):
                data[str(top)] = sub.tolist()
            else:
                data[str(top)] = {
                    str(c): sub[c].tolist() for c in sub.columns
                }
    else:
        for c in df.columns:
            data[str(c)] = df[c].tolist()
    index = df.index
    if isinstance(index, pd.DatetimeIndex):
        idx = [ts.isoformat() for ts in index]
    else:
        idx = index.tolist()
    return {"data": data, "index": idx}


def dict_to_frame(payload: Dict[str, Any]) -> pd.DataFrame:
    """Inverse of ``frame_to_dict``."""
    data = payload["data"]
    index = payload.get("index")
    columns = {}
    multi = any(isinstance(v, dict) for v in data.values())
    for top, v in data.items():
        if isinstance(v, dict):
            for sub, values in v.items():
                columns[(top, sub)] = values
        else:
            columns[(top, "") if multi else top] = v
    df = pd.DataFrame(columns)
    if multi:
        df.columns = pd.MultiIndex.from_tuples(df.columns)
    if index is not None:
        try:
            df.index = pd.DatetimeIndex(pd.to_datetime(index, utc=True))
        except (ValueError, TypeError):
            df.index = index
    return df


def extract_x_y(
    body: Optional[Dict[str, Any]],
    raw: Optional[bytes] = None,
    content_type: str = "application/json",
) -> Tuple[pd.DataFrame, Optional[pd.DataFrame]]:
    """Parse request payload into (X, y) DataFrames.

    JSON accepts ``{"X": [[...]] | {col: [...]}, "y": ..., "index": [...]}``;
    parquet bodies (content-type x-parquet) are read directly (reference
    supports both, SURVEY.md §2 "server").
    """
    if "parquet" in content_type:
        from gordo_components_tpu.utils.encoding import parquet_engine

        # engine pinned once (utils/encoding.py): skips pandas' "auto"
        # resolution (a first-chunk cold-start cost; the steady-state
        # parquet-vs-JSON story is in docs/architecture.md "Wire
        # protocol" — the response side is why parquet never won)
        df = pd.read_parquet(io.BytesIO(raw), engine=parquet_engine() or "auto")
        # supervised targets ride in the same file under a __y__ prefix
        # (client/client.py::_post_parquet): split them back out
        ycols = [c for c in df.columns if str(c).startswith("__y__")]
        if ycols:
            y = df[ycols].rename(columns=lambda c: str(c)[len("__y__"):])
            return df.drop(columns=ycols), y
        return df, None
    if not body or "X" not in body:
        raise ValueError("Request must contain 'X'")
    X = _parse_matrix(body["X"], body.get("index"))
    y = _parse_matrix(body["y"], body.get("index")) if body.get("y") is not None else None
    return X, y


def _parse_matrix(value, index=None) -> pd.DataFrame:
    if isinstance(value, dict):
        df = pd.DataFrame(value)
    else:
        arr = np.asarray(value, dtype="float32")
        if arr.ndim == 1:
            arr = arr[:, None]
        df = pd.DataFrame(arr)
    if index is not None and len(index) == len(df):
        try:
            df.index = pd.DatetimeIndex(pd.to_datetime(index, utc=True))
        except (ValueError, TypeError):
            df.index = index
    return df
