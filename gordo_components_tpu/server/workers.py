"""Multi-worker serving: N event loops behind one accept path.

PR 10 made the *bytes* cheap (framed tensor bodies, ``utils/wire.py``);
what still serializes every request is the single Python event loop that
accepts, parses, and dispatches them. This module runs ``GORDO_SERVER_WORKERS``
worker event loops — each a full aiohttp server parsing requests on its
own thread — over ONE shared application state:

- **worker 0 is the primary**: its loop runs the app's startup hooks, so
  the batching engine, placement controller, SLO sampler, and streaming
  plane all live there, exactly as in single-worker mode;
- **workers 1..N-1 are parse/dispatch loops**: same routes, same
  middleware, same state dict (collection, bank, quarantine, stats, …).
  Scoring hops to the engine's loop through
  :meth:`BatchingEngine.submit` — the device work was never
  loop-parallel (it batches better when funneled), but request parse,
  JSON/tensor decode, and response serialization now run N-wide;
- **accept path**: every worker binds its own listening socket with
  ``SO_REUSEPORT`` where the platform has it (the kernel load-balances
  accepts); otherwise a tiny in-process acceptor thread owns the one
  listening socket and hands accepted connections to worker loops
  round-robin (``loop.connect_accepted_socket``).

Shared-state rule: the pool installs a ``threading.Lock`` as
``stats["lock"]`` so the middleware's counters cannot lose increments
across worker threads; with workers=1 the lock is absent and the
middleware's mutation path is byte-for-byte the old single-loop one.
Each worker's app is tagged (``app.gordo_worker``) so requests count
into ``gordo_server_worker_requests_total{worker}`` and the ``/stats``
``workers`` block — the accept-skew view.

The worker apps share the primary's state dict by construction: a
``/reload`` or rebalance swapping ``app["bank"]`` on any worker's loop
is immediately visible to every other worker (the reload lock is
cross-loop — server/utils.py:CrossLoopLock — so rebuilds still
serialize).
"""

import asyncio
import contextlib
import logging
import os
import socket
import threading
from typing import List, Optional

from aiohttp import web

logger = logging.getLogger(__name__)


def resolve_workers(workers: Optional[int] = None) -> int:
    """The worker count: explicit argument, else ``GORDO_SERVER_WORKERS``
    (default 1 — single-loop serving, the behavior-identical default)."""
    if workers is None:
        raw = os.environ.get("GORDO_SERVER_WORKERS", "1") or "1"
        try:
            workers = int(raw)
        except ValueError:
            raise ValueError(
                f"GORDO_SERVER_WORKERS must be an integer, got {raw!r}"
            ) from None
    return max(1, int(workers))


def make_worker_app(primary: web.Application, worker_id: int) -> web.Application:
    """A parse/dispatch worker app sharing the primary app's state.

    Same middleware + routes as ``build_app``; its state mapping IS the
    primary's (``_state`` is aiohttp's documented-by-usage storage dict —
    pinned by the test suite), so every handler sees one collection/bank/
    stats world and mutations propagate instantly in both directions.
    No startup hooks: background services (engine, placement, SLO,
    streaming) belong to the primary's loop only.
    """
    from gordo_components_tpu.server import CLIENT_MAX_SIZE, _stats_middleware
    from gordo_components_tpu.server.views import routes

    app = web.Application(
        client_max_size=CLIENT_MAX_SIZE, middlewares=[_stats_middleware]
    )
    app.add_routes(routes)
    # share, don't copy: a copied dict would freeze the worker's view of
    # app["bank"] at boot and a /reload would split the fleet's truth
    app._state = primary._state
    app.gordo_worker = f"w{worker_id}"
    return app


def _make_listen_socket(
    host: str, port: int, reuse_port: bool, backlog: int = 128
) -> socket.socket:
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    if reuse_port:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    sock.bind((host, port))
    sock.listen(backlog)
    sock.setblocking(False)
    return sock


class ServerPool:
    """N worker event loops serving one shared app state (see module
    docstring). ``start()`` returns once every worker is listening;
    ``stop()`` tears the pool down in reverse order (parse workers
    first, the primary — whose cleanup stops the engine — last)."""

    def __init__(
        self,
        app: web.Application,
        host: str = "0.0.0.0",
        port: int = 5555,
        workers: Optional[int] = None,
        uds_path: Optional[str] = None,
        shm_ring: Optional[str] = None,
        reuse_port: Optional[bool] = None,
        backlog: int = 128,
    ):
        self.app = app
        self.host = host
        self.port = int(port)
        self.workers = resolve_workers(workers)
        self.uds_path = uds_path
        self.shm_ring_name = shm_ring
        self.backlog = int(backlog)
        if reuse_port is None:
            reuse_port = hasattr(socket, "SO_REUSEPORT")
        self.reuse_port = bool(reuse_port)
        self._threads: List[threading.Thread] = []
        self._loops: List[Optional[asyncio.AbstractEventLoop]] = []
        self._runners: List[Optional[web.AppRunner]] = []
        self._sockets: List[socket.socket] = []
        self._acceptor: Optional[threading.Thread] = None
        self._accept_sock: Optional[socket.socket] = None
        self._shm_server = None
        self._stop_evt = threading.Event()
        self._started = False

    # ------------------------------------------------------------------ #

    def start(self, timeout: float = 60.0) -> None:
        if self._started:
            raise RuntimeError("pool already started")
        self._started = True
        stats = self.app["stats"]
        if self.workers > 1 and stats.get("lock") is None:
            # the middleware's counters now mutate from N loop threads;
            # the lock restores the lost-increment-free contract
            stats["lock"] = threading.Lock()
        if self.workers > 1 and self.app.get("bank_enabled"):
            # one device-dispatch lock shared by every engine (the
            # primary's and each worker's): parse + coalesce run N-wide,
            # bank calls serialize where the device would anyway
            self.app["bank_dispatch_lock"] = threading.Lock()
            # worker engines register here so a bank swap (/reload,
            # rebalance, adaptation) can repoint ALL of them
            self.app["worker_engines"] = []
        transports = dict(self.app.get("transports") or {})
        if self.uds_path:
            # advertised through /models so a co-located client's
            # transport="auto" can find (and stat-check) the socket
            transports["uds"] = self.uds_path
        if self.shm_ring_name:
            transports["shm"] = self.shm_ring_name
        if transports:
            self.app["transports"] = transports
        # one socket per worker under SO_REUSEPORT (kernel balances the
        # accepts); one shared socket + acceptor thread otherwise
        per_worker_sockets: List[Optional[socket.socket]] = []
        if self.reuse_port:
            first = _make_listen_socket(
                self.host, self.port, True, self.backlog
            )
            self.port = first.getsockname()[1]  # resolve port=0 once
            per_worker_sockets.append(first)
            for _ in range(1, self.workers):
                per_worker_sockets.append(
                    _make_listen_socket(self.host, self.port, True, self.backlog)
                )
        else:
            self._accept_sock = _make_listen_socket(
                self.host, self.port, False, self.backlog
            )
            self._accept_sock.setblocking(True)
            self.port = self._accept_sock.getsockname()[1]
            per_worker_sockets = [None] * self.workers
        self._sockets = [s for s in per_worker_sockets if s is not None]

        apps = [self.app] + [
            make_worker_app(self.app, i) for i in range(1, self.workers)
        ]
        if self.workers > 1:
            # the primary parses too: tag it so the skew view is complete
            self.app.gordo_worker = "w0"
        self._loops = [None] * self.workers
        self._runners = [None] * self.workers
        ready = [threading.Event() for _ in range(self.workers)]
        errors: List[Optional[BaseException]] = [None] * self.workers
        for i in range(self.workers):
            t = threading.Thread(
                target=self._worker_main,
                args=(i, apps[i], per_worker_sockets[i], ready[i], errors),
                name=f"gordo-worker-{i}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()
        for i, evt in enumerate(ready):
            if not evt.wait(timeout):
                self.stop()
                raise RuntimeError(f"worker {i} did not become ready")
            if errors[i] is not None:
                self.stop()
                raise RuntimeError(f"worker {i} failed to start") from errors[i]
        if self._accept_sock is not None:
            self._acceptor = threading.Thread(
                target=self._accept_loop, name="gordo-acceptor", daemon=True
            )
            self._acceptor.start()
        if self.shm_ring_name:
            from gordo_components_tpu.server.transport import ShmServer

            self._shm_server = ShmServer.create(self.app, self.shm_ring_name)
        logger.info(
            "serving pool up: %d worker(s) on %s:%d%s%s (reuse_port=%s)",
            self.workers, self.host, self.port,
            f" + uds {self.uds_path}" if self.uds_path else "",
            f" + shm {self.shm_ring_name}" if self.shm_ring_name else "",
            self.reuse_port,
        )

    def _worker_main(self, idx, app, sock, ready_evt, errors) -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loops[idx] = loop
        runner = web.AppRunner(app, handle_signals=False)
        worker_engine = None
        try:
            loop.run_until_complete(runner.setup())
            self._runners[idx] = runner
            if idx > 0:
                worker_engine = self._start_worker_engine(app, loop, idx)
            if sock is not None:
                loop.run_until_complete(web.SockSite(runner, sock).start())
            if idx == 0 and self.uds_path:
                # ONE unix acceptor is plenty: UDS accept is not the
                # bottleneck its TCP sibling is, and the parse work a
                # UDS request brings still lands on whichever loop the
                # kernel wakes — here, the primary's
                loop.run_until_complete(
                    web.UnixSite(runner, self.uds_path).start()
                )
        except BaseException as exc:  # startup failed: report, don't hang
            errors[idx] = exc
            ready_evt.set()
            with contextlib.suppress(Exception):
                loop.run_until_complete(runner.cleanup())
            loop.close()
            return
        ready_evt.set()
        try:
            loop.run_forever()
        finally:
            if worker_engine is not None:
                with contextlib.suppress(Exception):
                    loop.run_until_complete(worker_engine.stop())
            with contextlib.suppress(Exception):
                loop.run_until_complete(runner.cleanup())
            with contextlib.suppress(Exception):
                loop.run_until_complete(loop.shutdown_asyncgens())
            loop.close()

    def _start_worker_engine(self, app, loop, idx):
        """A local batching engine for this worker's loop, over the one
        shared bank: requests parsed here never pay a cross-loop hop,
        coalescing stays loop-local, and the shared dispatch lock
        serializes the bank calls the device would serialize anyway.
        Uninstrumented (registry=False): the primary engine keeps the
        ``gordo_engine_*`` metric surface; per-worker counters surface
        through /stats ``worker_engines``."""
        from gordo_components_tpu.server.bank import BatchingEngine

        bank = self.app.get("bank")
        lock = self.app.get("bank_dispatch_lock")
        if bank is None or lock is None or not len(bank):
            return None
        cfg = self.app.get("bank_config") or {}
        engine = BatchingEngine(
            bank,
            max_batch=cfg.get("max_batch", 64),
            flush_ms=cfg.get("flush_ms", 2.0),
            max_queue=cfg.get("max_queue"),
            registry=False,
            dispatch_lock=lock,
        )
        loop.call_soon(engine.start)
        app.gordo_engine = engine
        self.app["worker_engines"].append((f"w{idx}", engine))
        return engine

    def _accept_loop(self) -> None:
        """SO_REUSEPORT-less fallback: one blocking acceptor handing
        connections to worker loops round-robin. The hand-off is a
        thread-safe hop onto the target loop, which adopts the connected
        socket into its own aiohttp protocol stack."""
        assert self._accept_sock is not None
        idx = 0
        while not self._stop_evt.is_set():
            try:
                conn, _peer = self._accept_sock.accept()
            except OSError:
                break  # socket closed by stop()
            loop = self._loops[idx % self.workers]
            runner = self._runners[idx % self.workers]
            idx += 1
            if loop is None or runner is None or not loop.is_running():
                conn.close()
                continue
            conn.setblocking(False)

            async def _adopt_coro(conn=conn, runner=runner):
                # runner.server is the aiohttp protocol factory for this
                # worker's app
                await asyncio.get_running_loop().connect_accepted_socket(
                    runner.server, conn
                )

            asyncio.run_coroutine_threadsafe(_adopt_coro(), loop)

    # ------------------------------------------------------------------ #

    def stop(self, timeout: float = 30.0) -> None:
        self._stop_evt.set()
        if self._shm_server is not None:
            self._shm_server.close()
            self._shm_server = None
        if self._accept_sock is not None:
            with contextlib.suppress(OSError):
                self._accept_sock.close()
        if self._acceptor is not None:
            self._acceptor.join(timeout)
        # parse workers first; the primary last — its cleanup stops the
        # engine, and in-flight worker requests may still be awaiting it
        for i in range(self.workers - 1, -1, -1):
            loop = self._loops[i] if i < len(self._loops) else None
            if loop is not None and loop.is_running():
                loop.call_soon_threadsafe(loop.stop)
            if i < len(self._threads):
                self._threads[i].join(timeout)
        for sock in self._sockets:
            with contextlib.suppress(OSError):
                sock.close()
        if self.uds_path and os.path.exists(self.uds_path):
            with contextlib.suppress(OSError):
                os.unlink(self.uds_path)

    def wait(self) -> None:
        """Block the calling (main) thread until interrupted — the
        ``run_server`` CLI's foreground behavior."""
        try:
            while any(t.is_alive() for t in self._threads):
                for t in self._threads:
                    t.join(1.0)
        except KeyboardInterrupt:
            pass


__all__ = ["ServerPool", "make_worker_app", "resolve_workers"]
