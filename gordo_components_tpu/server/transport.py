"""Transport-agnostic scoring core + the shared-memory ring server.

The HTTP views own the request/response *protocol* (headers, deadlines,
traces); this module owns the part every transport shares — "GTNS bytes
in, GTNS bytes out, through the same engine/bank the HTTP path uses" —
so the shared-memory ring (utils/shm_ring.py) answers byte-identically
to a TCP or UDS POST of the same body (the bitwise cross-transport
parity contract in tests/test_wire.py).
"""

import json
import logging
import threading
import time
from typing import Optional, Tuple

import numpy as np

from gordo_components_tpu.qos.admission import QosShed
from gordo_components_tpu.qos.classify import classify_meta
from gordo_components_tpu.server.model_io import (
    anomaly_frame_arrays,
    decode_tensor_request_ex,
    encode_anomaly_response,
    encode_prediction_response,
)
from gordo_components_tpu.utils.shm_ring import (
    BUSY,
    DEFAULT_SLOT_MB,
    DEFAULT_SLOTS,
    REQ,
    ShmRing,
    ShmRingError,
    unpack_envelope,
    _IDLE_SLEEP_MAX,
    _IDLE_SLEEP_MIN,
)
from gordo_components_tpu.utils.wire import WireFormatError

logger = logging.getLogger(__name__)


def _err(status: int, body: dict) -> Tuple[int, bytes]:
    return status, json.dumps(body).encode("utf-8")


def _note_result(app, target: str, X_arr, values) -> None:
    """The quarantine breaker's verdict, transport-side: same rule as
    views._note_scoring_result (finite output resets the streak;
    non-finite output from finite input counts), minus the HTTP-only
    goodput stash."""
    quarantine = app.get("quarantine")
    if quarantine is None:
        return
    arr = np.asarray(values)
    finite = bool(np.all(np.isfinite(arr)))
    if finite:
        quarantine.record_success(target)
    elif bool(np.all(np.isfinite(np.asarray(X_arr)))):
        quarantine.record_failure(target, "non-finite scores in model output")


def score_tensor_blocking(
    app, target: str, raw, endpoint: str = "anomaly"
) -> Tuple[int, bytes]:
    """Score one ``GTNS`` request body exactly as the HTTP tensor path
    would, from a plain thread. Returns ``(status, response_bytes)``:
    200 bodies are the same ``encode_*_response`` bytes the views emit;
    error statuses carry the same JSON error documents (404 unknown
    target, 410 quarantine with reason, 400 malformed/model error, 429
    overload) — so a producer can switch transports without changing
    its error handling.

    ``raw`` may be a memoryview straight over a mapped shm slot: the
    decode is ``np.frombuffer`` views over it (zero-copy end to end
    until the bank's own coalescing stage).
    """
    from gordo_components_tpu.resilience.deadline import DeadlineExceeded
    from gordo_components_tpu.server.bank import EngineOverloaded

    collection = app["collection"]
    try:
        model, _meta = collection.entry(target)
    except KeyError:
        return _err(404, {"error": f"No such model: {target}"})
    quarantine = app.get("quarantine")
    if quarantine is not None and target in quarantine:
        info = quarantine.reason(target) or {}
        return _err(
            410,
            {
                "error": f"Model {target!r} is quarantined",
                "reason": info.get("reason"),
                "failures": info.get("failures"),
                "since": info.get("since"),
            },
        )
    if endpoint == "anomaly" and not hasattr(model, "anomaly"):
        return _err(422, {"error": "Model does not support anomaly scoring"})
    try:
        Xf, yf, meta = decode_tensor_request_ex(raw)
    except WireFormatError as exc:
        return _err(400, {"error": f"tensor body: {exc}"})
    engine = app.get("bank_engine")
    banked = engine is not None and target in getattr(engine, "bank", ())
    # QoS on the header-less transports: the __meta__ sidecar is the
    # ONLY identity carrier here, and admission runs the same controller
    # as the HTTP path — the shm ring must not be a fairness bypass
    qos = classify_meta(meta)
    tenant_label = "default"
    admission = app.get("qos_admission")
    if admission is not None:
        depth = engine._queue.qsize() if banked else 0
        try:
            tenant_label = admission.admit(
                qos,
                queue_depth=depth,
                max_queue=getattr(engine, "max_queue", 0) if banked else 0,
                drain_s=(
                    engine.drain_estimate(depth)
                    if banked and hasattr(engine, "drain_estimate")
                    else 0.05
                ),
            )
        except QosShed as exc:
            return _err(
                429,
                {
                    "error": str(exc),
                    "reason": exc.reason,
                    "tenant": exc.tenant,
                    "class": exc.qos_class,
                    "retry_after_s": round(exc.retry_after_s, 2),
                },
            )
    try:
        if endpoint == "anomaly":
            if banked:
                result = engine.score_blocking(
                    target, Xf, yf,
                    tenant=tenant_label, qos_class=qos.qos_class,
                )
                body = encode_anomaly_response(
                    result.tags, result.to_arrays(), result.offset
                )
                total_scaled = result.total_scaled
            else:
                import pandas as pd

                frame = model.anomaly(
                    pd.DataFrame(Xf), None if yf is None else pd.DataFrame(yf)
                )
                body = encode_anomaly_response(
                    frame["model-input"].columns,
                    anomaly_frame_arrays(frame),
                    len(Xf) - len(frame),
                )
                total_scaled = frame[("total-anomaly-scaled", "")].to_numpy()
            _note_result(app, target, Xf, total_scaled)
            return 200, body
        if banked:
            result = engine.score_blocking(
                target, Xf, tenant=tenant_label, qos_class=qos.qos_class
            )
            output = result.model_output
        else:
            output = model.predict(Xf)
        _note_result(app, target, Xf, output)
        return 200, encode_prediction_response(output, len(Xf))
    except EngineOverloaded as exc:
        return _err(
            429,
            {
                "error": str(exc),
                "reason": "engine_overloaded",
                "retry_after_s": round(exc.retry_after_s, 2),
            },
        )
    except DeadlineExceeded as exc:
        return _err(504, {"error": str(exc)})
    except Exception as exc:
        # same contract as the views: model errors are 400s with detail,
        # and only non-input-shaped failures count against the breaker
        if quarantine is not None and not isinstance(
            exc, (ValueError, KeyError)
        ):
            quarantine.record_failure(target, f"{type(exc).__name__}: {exc}")
        logger.exception("shm scoring failed for %r", target)
        return _err(400, {"error": f"{type(exc).__name__}: {exc}"})


class ShmServer:
    """The server end of the scoring ring: one poll thread that parses
    ``REQ`` slots straight off the mapped segment and answers in place.

    Scoring funnels through the SAME batching engine the HTTP handlers
    use (``BatchingEngine.score_blocking`` hops onto the engine's loop),
    so shm requests coalesce into the same device batches as TCP/UDS
    traffic — the transports differ in copies, never in math. Counters
    land in ``stats["shm"]`` (surfaced via ``/stats`` and
    ``gordo_shm_requests_total``); this thread is their only writer.
    """

    def __init__(self, app, ring: ShmRing):
        from concurrent.futures import ThreadPoolExecutor

        self.app = app
        self.ring = ring
        self.stats = {"requests": 0, "errors": 0, "bytes_in": 0, "bytes_out": 0}
        self._stats_lock = threading.Lock()
        app["stats"]["shm"] = self.stats
        self._stop = threading.Event()
        # slots are served CONCURRENTLY (one pool worker per in-flight
        # slot): N producers' requests reach the engine together and
        # coalesce into the same device batches as HTTP traffic — a
        # serial slot loop would cap the ring at one dispatch per round
        # trip and waste the batching the engine exists for
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, min(ring.slots, 8)),
            thread_name_prefix="gordo-shm-worker",
        )
        self._thread = threading.Thread(
            target=self._run, name="gordo-shm-server", daemon=True
        )
        self._thread.start()

    @classmethod
    def create(
        cls,
        app,
        name: str,
        slots: Optional[int] = None,
        slot_mb: Optional[float] = None,
    ) -> "ShmServer":
        import os

        if slots is None:
            slots = int(os.environ.get("GORDO_SHM_SLOTS", DEFAULT_SLOTS))
        if slot_mb is None:
            slot_mb = float(os.environ.get("GORDO_SHM_SLOT_MB", DEFAULT_SLOT_MB))
        ring = ShmRing.create(name, slots=slots, slot_mb=slot_mb)
        transports = dict(app.get("transports") or {})
        transports["shm"] = name
        app["transports"] = transports
        return cls(app, ring)

    def _run(self) -> None:
        sleep = _IDLE_SLEEP_MIN
        while not self._stop.is_set():
            dispatched = 0
            for i in range(self.ring.slots):
                if self.ring.closed or self._stop.is_set():
                    return
                if self.ring.state(i) != REQ:
                    continue
                self.ring.set_state(i, BUSY)
                dispatched += 1
                self._pool.submit(self._serve_slot, i)
            if dispatched:
                sleep = _IDLE_SLEEP_MIN
            else:
                time.sleep(sleep)
                sleep = min(sleep * 2, _IDLE_SLEEP_MAX)

    def _serve_slot(self, i: int) -> None:
        n_in = 0
        try:
            payload = self.ring.request_view(i)
            target, endpoint, body = unpack_envelope(payload)
            n_in = len(body)
            status, resp = score_tensor_blocking(self.app, target, body, endpoint)
        except (ShmRingError, Exception) as exc:  # noqa: BLE001
            status, resp = _err(400, {"error": f"{type(exc).__name__}: {exc}"})
        with self._stats_lock:
            self.stats["requests"] += 1
            self.stats["bytes_in"] += n_in
            if status >= 400:
                self.stats["errors"] += 1
            self.stats["bytes_out"] += len(resp)
        try:
            self.ring.write_response(i, status, resp)
        except Exception:
            logger.exception("failed to answer shm slot %d", i)

    def close(self) -> None:
        self._stop.set()
        self._thread.join(10.0)
        self._pool.shutdown(wait=True)
        self.ring.close()


__all__ = ["ShmServer", "score_tensor_blocking"]
