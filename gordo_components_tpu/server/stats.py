"""Serving latency observability (VERDICT r3 next #4).

Reference parity: the reference exposed per-pod latency through its
gunicorn/Flask access logs + Prometheus sidecars (SURVEY.md §5
metrics/observability); here the serving process records its own
fixed-bin latency histograms and ``GET .../stats`` reports percentiles,
because at fleet scale the interesting number is the tail produced by
the coalescing window, not the mean.

Log-spaced fixed bins: O(1) record (two float ops + an int increment),
O(bins) percentile read, zero allocation on the hot path, and a bounded
memory footprint no matter how many requests pass through — the standard
histogram trade (one-bin-width relative error, here ~26% per bin =
10 bins/decade) that Prometheus/HDRHistogram users expect.

Single-writer contract: all ``record`` sites run on the aiohttp event
loop thread (middleware + BatchingEngine loop), so plain int increments
are safe without locks. Snapshot readers (the /stats handler) run on the
same loop.
"""

import math

__all__ = ["LatencyHistogram"]

# 50us .. ~100s at 10 bins/decade; everything slower lands in overflow
_LO_S = 5e-5
_BINS_PER_DECADE = 10
_N_BINS = int(math.ceil(math.log10(100.0 / _LO_S) * _BINS_PER_DECADE)) + 1
_LOG_LO = math.log10(_LO_S)


class LatencyHistogram:
    """Latency histogram over log-spaced bins with percentile reads."""

    __slots__ = ("counts", "count", "sum", "max")

    def __init__(self):
        self.counts = [0] * (_N_BINS + 1)  # +1: overflow bin
        self.count = 0
        self.sum = 0.0
        self.max = 0.0

    def record(self, seconds: float) -> None:
        if seconds < 0:  # clock weirdness must not corrupt the histogram
            seconds = 0.0
        if seconds <= _LO_S:
            idx = 0
        else:
            idx = min(
                _N_BINS,
                1 + int((math.log10(seconds) - _LOG_LO) * _BINS_PER_DECADE),
            )
        self.counts[idx] += 1
        self.count += 1
        self.sum += seconds
        if seconds > self.max:
            self.max = seconds

    def percentile(self, q: float) -> float:
        """Upper edge of the bin containing the q-quantile observation, in
        seconds (<= one bin width above the true value). 0.0 when empty."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank and c:
                if i >= _N_BINS:
                    return self.max  # overflow bin: max is exact
                # clamp to the exact max: a bin's upper edge can exceed
                # every value ever recorded into it
                return min(self.max, 10 ** (_LOG_LO + i / _BINS_PER_DECADE))
        return self.max

    def snapshot(self) -> dict:
        """Compact JSON-ready summary for ``/stats``."""
        if self.count == 0:
            return {"count": 0}
        ms = 1e3
        return {
            "count": self.count,
            "mean_ms": round(self.sum / self.count * ms, 3),
            "p50_ms": round(self.percentile(0.50) * ms, 3),
            "p95_ms": round(self.percentile(0.95) * ms, 3),
            "p99_ms": round(self.percentile(0.99) * ms, 3),
            "max_ms": round(self.max * ms, 3),
        }
