"""Serving latency observability (VERDICT r3 next #4).

Reference parity: the reference exposed per-pod latency through its
gunicorn/Flask access logs + Prometheus sidecars (SURVEY.md §5
metrics/observability); here the serving process records its own
fixed-bin latency histograms and ``GET .../stats`` reports percentiles,
because at fleet scale the interesting number is the tail produced by
the coalescing window, not the mean.

The log-binned histogram itself now lives in
``gordo_components_tpu.observability.metrics`` (generalized to arbitrary
value ranges so batch sizes and row counts histogram too, and exposed in
Prometheus text format through the metrics registry); this module keeps
the serving-flavored name and its single-writer contract documentation.

Single-writer contract: all ``record`` sites run on the aiohttp event
loop thread (middleware + BatchingEngine loop), so plain int increments
are safe without locks. Snapshot readers (the /stats handler) run on the
same loop.
"""

from gordo_components_tpu.observability.metrics import Histogram

__all__ = ["LatencyHistogram"]


class LatencyHistogram(Histogram):
    """Latency histogram over log-spaced bins with percentile reads.

    50us .. ~100s at 10 bins/decade (everything slower lands in the
    overflow bin, where the tracked exact max is the reported bound) —
    the defaults the serving stack has always used."""

    __slots__ = ()
