"""Serving latency observability (VERDICT r3 next #4).

Reference parity: the reference exposed per-pod latency through its
gunicorn/Flask access logs + Prometheus sidecars (SURVEY.md §5
metrics/observability); here the serving process records its own
fixed-bin latency histograms and ``GET .../stats`` reports percentiles,
because at fleet scale the interesting number is the tail produced by
the coalescing window, not the mean.

The log-binned histogram itself now lives in
``gordo_components_tpu.observability.metrics`` (generalized to arbitrary
value ranges so batch sizes and row counts histogram too, and exposed in
Prometheus text format through the metrics registry); this module keeps
the serving-flavored name and its single-writer contract documentation.

Single-writer contract: all ``record`` sites run on the aiohttp event
loop thread (middleware + BatchingEngine loop), so plain int increments
are safe without locks. Snapshot readers (the /stats handler) run on the
same loop.
"""

from gordo_components_tpu.observability.metrics import (
    LATENCY_BINS_PER_DECADE,
    Histogram,
)

__all__ = ["LatencyHistogram"]


class LatencyHistogram(Histogram):
    """Latency histogram over log-spaced bins with percentile reads.

    50us .. ~100s at 32 bins/decade (everything slower lands in the
    overflow bin, where the tracked exact max is the reported bound).

    Bin-count audit (ISSUE 7 satellite): the original 10 bins/decade
    bounded percentile error at one bin width — up to ~26% relative —
    which is fine for "is p99 40ms or 4s" but blurs exactly the 1–50 ms
    range where PR 4's deadline budgets live (a 20 ms budget and a 25 ms
    p99 landed in the same bin). 32 bins/decade bounds the error at
    10^(1/32)−1 ≈ 7.5% across the whole range — low-ms included — for
    ~3x the (still O(200)-int) memory; the regression test in
    tests/test_stats.py holds the bound at ≤10%. The resolution knob is
    ``observability.metrics.LATENCY_BINS_PER_DECADE``, shared with the
    goodput ledger's SLO histogram so the two cannot diverge. The generic
    :class:`Histogram` default stays at 10/decade: batch-size and
    row-count histograms don't need ms-grade resolution."""

    __slots__ = ()

    def __init__(
        self,
        lo: float = 5e-5,
        hi: float = 100.0,
        bins_per_decade: int = LATENCY_BINS_PER_DECADE,
    ):
        super().__init__(lo=lo, hi=hi, bins_per_decade=bins_per_decade)
