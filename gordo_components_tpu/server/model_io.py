"""Model loading for the server.

Reference parity: gordo_components/server/model_io.py (unverified; SURVEY.md
§2 "server") — the reference loads ONE artifact per server process (env
``MODEL_LOCATION``). The TPU-native server instead serves a *collection*:
a directory of per-machine artifact dirs loaded into one process so a whole
fleet shares a chip's HBM (BASELINE.json config 5); a single artifact dir
still works and behaves like the reference.
"""

import logging
import os
from typing import Any, Dict, Optional

from gordo_components_tpu import serializer

logger = logging.getLogger(__name__)


class ModelCollection:
    """name -> (model, metadata) for every artifact under ``root``.

    ``root`` may be a single artifact dir (containing ``model.pkl``) —
    loaded under the name ``target_name or basename(root)`` — or a dir of
    artifact subdirs, each loaded under its subdir name.

    :meth:`refresh` rescans the root and incrementally loads new or
    changed artifacts (by ``model.pkl`` mtime) and drops removed ones, so
    a running server can pick up freshly built fleet artifacts without a
    restart (the reference redeployed a pod per model instead).
    """

    def __init__(self, root: str, target_name: Optional[str] = None):
        self.root = root
        self.target_name = target_name
        self.models: Dict[str, Any] = {}
        self.metadata: Dict[str, Dict] = {}
        self._mtimes: Dict[str, float] = {}
        self.refresh()
        if not self.models:
            raise FileNotFoundError(f"No model artifacts found under {root!r}")

    def _scan(self) -> Dict[str, str]:
        """name -> artifact dir for the current on-disk state."""
        if os.path.exists(os.path.join(self.root, "model.pkl")):
            name = self.target_name or os.path.basename(os.path.normpath(self.root))
            return {name: self.root}
        out = {}
        try:
            entries = sorted(os.listdir(self.root))
        except FileNotFoundError:
            return {}
        for entry in entries:
            path = os.path.join(self.root, entry)
            if os.path.isdir(path) and os.path.exists(os.path.join(path, "model.pkl")):
                out[entry] = path
        return out

    def refresh(self) -> Dict[str, list]:
        """Incremental rescan. Returns {"added": [...], "updated": [...],
        "removed": [...]} by model name."""
        on_disk = self._scan()
        added, updated, removed = [], [], []
        for name in list(self.models):
            if name not in on_disk:
                removed.append(name)
                del self.models[name]
                del self.metadata[name]
                self._mtimes.pop(name, None)
        for name, path in on_disk.items():
            try:
                mtime = os.path.getmtime(os.path.join(path, "model.pkl"))
            except OSError:
                continue
            if name not in self.models:
                self._load_one(name, path)
                self._mtimes[name] = mtime
                added.append(name)
            elif mtime != self._mtimes.get(name):
                self._load_one(name, path)
                self._mtimes[name] = mtime
                updated.append(name)
        if added or updated or removed:
            logger.info(
                "Collection refresh: +%d ~%d -%d (now %d models)",
                len(added), len(updated), len(removed), len(self.models),
            )
        return {"added": added, "updated": updated, "removed": removed}

    def _load_one(self, name: str, path: str) -> None:
        logger.info("Loading model %r from %s", name, path)
        self.models[name] = serializer.load(path)
        meta = serializer.load_metadata(path)
        # serve the artifact's recorded name if present
        meta.setdefault("name", name)
        self.metadata[name] = meta

    def __contains__(self, name: str) -> bool:
        return name in self.models

    def __getitem__(self, name: str):
        return self.models[name]

    def names(self):
        return sorted(self.models)
