"""Model loading for the server.

Reference parity: gordo_components/server/model_io.py (unverified; SURVEY.md
§2 "server") — the reference loads ONE artifact per server process (env
``MODEL_LOCATION``). The TPU-native server instead serves a *collection*:
a directory of per-machine artifact dirs loaded into one process so a whole
fleet shares a chip's HBM (BASELINE.json config 5); a single artifact dir
still works and behaves like the reference.
"""

import logging
import os
from typing import Any, Dict, Optional

from gordo_components_tpu import serializer

logger = logging.getLogger(__name__)


class ModelCollection:
    """name -> (model, metadata) for every artifact under ``root``.

    ``root`` may be a single artifact dir (containing ``model.pkl``) —
    loaded under the name ``target_name or basename(root)`` — or a dir of
    artifact subdirs, each loaded under its subdir name.
    """

    def __init__(self, root: str, target_name: Optional[str] = None):
        self.root = root
        self.models: Dict[str, Any] = {}
        self.metadata: Dict[str, Dict] = {}
        if os.path.exists(os.path.join(root, "model.pkl")):
            name = target_name or os.path.basename(os.path.normpath(root))
            self._load_one(name, root)
        else:
            for entry in sorted(os.listdir(root)):
                path = os.path.join(root, entry)
                if os.path.isdir(path) and os.path.exists(
                    os.path.join(path, "model.pkl")
                ):
                    self._load_one(entry, path)
        if not self.models:
            raise FileNotFoundError(f"No model artifacts found under {root!r}")

    def _load_one(self, name: str, path: str) -> None:
        logger.info("Loading model %r from %s", name, path)
        self.models[name] = serializer.load(path)
        meta = serializer.load_metadata(path)
        # serve the artifact's recorded name if present
        meta.setdefault("name", name)
        self.metadata[name] = meta

    def __contains__(self, name: str) -> bool:
        return name in self.models

    def __getitem__(self, name: str):
        return self.models[name]

    def names(self):
        return sorted(self.models)
