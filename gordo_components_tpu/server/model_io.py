"""Model loading + model-boundary wire I/O for the server.

Reference parity: gordo_components/server/model_io.py (unverified; SURVEY.md
§2 "server") — the reference loads ONE artifact per server process (env
``MODEL_LOCATION``). The TPU-native server instead serves a *collection*:
a directory of per-machine artifact dirs loaded into one process so a whole
fleet shares a chip's HBM (BASELINE.json config 5); a single artifact dir
still works and behaves like the reference.

Also the binary scoring data plane's server half (PR 10): decode a
``application/x-gordo-tensor`` request body straight into the float32
arrays the bank scores (``np.frombuffer`` view, no DataFrame), and encode
score arrays straight into one preallocated response body (utils/wire.py).
"""

import io
import json
import logging
import os
import tarfile
from typing import Any, Dict, Optional, Tuple

import numpy as np

from gordo_components_tpu import serializer
from gordo_components_tpu.resilience.faults import faultpoint
from gordo_components_tpu.utils.wire import (
    ANOMALY_FRAME_NAMES,
    WireFormatError,
    pack_frames,
    rows_as_f32,
    unpack_frames,
)

logger = logging.getLogger(__name__)


def decode_tensor_request(
    raw: bytes,
) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Tensor request body -> ``(X, y)`` float32 arrays.

    The body must carry an ``X`` frame (rows x features); ``y`` is
    optional (supervised targets). Native little-endian float32 payloads
    come back as zero-copy read-only views of ``raw`` — the bank's
    coalescing stage copies rows into arena staging buffers anyway, so
    nothing downstream needs writability. Raises
    :class:`~gordo_components_tpu.utils.wire.WireFormatError` (-> 400
    with the reason) on malformed bodies.
    """
    X, y, _ = decode_tensor_request_ex(raw)
    return X, y


def decode_tensor_request_ex(
    raw: bytes,
) -> Tuple[np.ndarray, Optional[np.ndarray], Optional[Dict[str, Any]]]:
    """:func:`decode_tensor_request` plus the request's ``__meta__``
    sidecar (or None): the binary path's carrier for non-tensor request
    facts — today the QoS identity (``{"tenant", "priority"}``,
    qos/classify.py), which must survive transports that have no
    headers (the shm envelope) or proxies that strip custom ones. A
    malformed sidecar is ignored, not a 400: QoS tagging is best-effort
    metadata, never a reason to refuse a well-formed tensor body."""
    frames = unpack_frames(raw)
    if "X" not in frames:
        raise WireFormatError(
            f"tensor body must carry an 'X' frame (got {sorted(frames)})"
        )
    X = rows_as_f32(frames["X"], "X")
    y = rows_as_f32(frames["y"], "y") if "y" in frames else None
    if y is not None and len(y) != len(X):
        raise WireFormatError(
            f"y has {len(y)} rows but X has {len(X)}"
        )
    meta: Optional[Dict[str, Any]] = None
    if "__meta__" in frames:
        try:
            doc = json.loads(np.asarray(frames["__meta__"], np.uint8).tobytes())
            if isinstance(doc, dict):
                meta = doc
        except (ValueError, TypeError):
            pass
    return X, y, meta


def _meta_frame(meta: Dict[str, Any]) -> Tuple[str, np.ndarray]:
    """Small JSON sidecar riding as a u1 frame: offsets/tags — the few
    non-tensor facts a client needs to reassemble an indexed frame."""
    return "__meta__", np.frombuffer(json.dumps(meta).encode("utf-8"), np.uint8)


def encode_prediction_response(output: np.ndarray, n_input_rows: int) -> bytes:
    """``POST /prediction`` tensor response: a ``data`` frame plus the
    sequence-warmup ``offset`` (output row i is input row i + offset) in
    ``__meta__`` — the client trims its own index by it, replacing the
    JSON body's stringified index round-trip."""
    output = np.asarray(output)
    return pack_frames(
        [
            _meta_frame({"offset": int(n_input_rows - len(output))}),
            ("data", output),
        ]
    )


def encode_anomaly_response(
    tags, arrays: Dict[str, np.ndarray], offset: int
) -> bytes:
    """``POST /anomaly/prediction`` tensor response: the six score arrays
    (``ScoreResult.to_arrays`` order) written into one preallocated body
    — no DataFrame assembly, no per-column ``tolist``."""
    meta = _meta_frame({"offset": int(offset), "tags": [str(t) for t in tags]})
    return pack_frames(
        [meta] + [(name, arrays[name]) for name in ANOMALY_FRAME_NAMES]
    )


def anomaly_frame_arrays(frame) -> Dict[str, np.ndarray]:
    """The wire arrays from an assembled anomaly DataFrame — the
    per-model fallback path scores through ``model.anomaly`` (which
    builds the frame); the banked path never builds one
    (``ScoreResult.to_arrays``)."""
    return {
        "model-input": frame["model-input"].to_numpy(),
        "model-output": frame["model-output"].to_numpy(),
        "tag-anomaly-unscaled": frame["tag-anomaly-unscaled"].to_numpy(),
        "tag-anomaly-scaled": frame["tag-anomaly-scaled"].to_numpy(),
        "total-anomaly-unscaled": frame[("total-anomaly-unscaled", "")].to_numpy(),
        "total-anomaly-scaled": frame[("total-anomaly-scaled", "")].to_numpy(),
    }

# chaos site: artifact deserialization (tests/test_chaos.py drives it);
# firing inside _load_one lands the failure in refresh()'s per-entry
# isolation, exactly where a truly corrupt artifact would surface
_FP_LOAD = faultpoint("model_io.load")


def pack_artifact_dir(path: str) -> bytes:
    """One member's artifact dir as a gzipped tar (the cross-replica
    shipping format for mesh migrations). Paths inside the archive are
    relative to the dir, so the receiver lands them under its own root
    regardless of the sender's layout."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        for entry in sorted(os.listdir(path)):
            tar.add(os.path.join(path, entry), arcname=entry)
    return buf.getvalue()


def unpack_artifact_dir(raw: bytes, dest: str) -> None:
    """Extract a shipped artifact archive under ``dest``, validating
    every member name first — the archive crosses a network boundary, so
    absolute paths, ``..`` traversal, links, and devices are rejected
    outright (a hostile or corrupted archive must not write outside the
    member's own dir)."""
    os.makedirs(dest, exist_ok=True)
    with tarfile.open(fileobj=io.BytesIO(raw), mode="r:gz") as tar:
        for member in tar.getmembers():
            name = member.name
            if (
                os.path.isabs(name)
                or ".." in name.split("/")
                or not (member.isfile() or member.isdir())
            ):
                raise ValueError(
                    f"refusing artifact archive member {name!r} "
                    "(unsafe path or non-file entry)"
                )
        for member in tar.getmembers():
            tar.extract(member, dest, set_attrs=False)


def scan_artifacts(root: str, target_name: Optional[str] = None) -> Dict[str, str]:
    """name -> artifact dir for the on-disk state under ``root`` (a
    single artifact dir, or a dir of artifact subdirs). Module-level so
    the mesh bootstrap can compute the FULL fleet roster — every replica
    must partition the same global member list — before the collection
    filters down to this replica's slice."""
    if os.path.exists(os.path.join(root, "model.pkl")):
        name = target_name or os.path.basename(os.path.normpath(root))
        return {name: root}
    out = {}
    try:
        entries = sorted(os.listdir(root))
    except FileNotFoundError:
        return {}
    for entry in entries:
        path = os.path.join(root, entry)
        if os.path.isdir(path) and os.path.exists(os.path.join(path, "model.pkl")):
            out[entry] = path
    return out


class ModelCollection:
    """name -> (model, metadata) for every artifact under ``root``.

    ``root`` may be a single artifact dir (containing ``model.pkl``) —
    loaded under the name ``target_name or basename(root)`` — or a dir of
    artifact subdirs, each loaded under its subdir name.

    :meth:`refresh` rescans the root and incrementally loads new or
    changed artifacts (by ``model.pkl`` mtime) and drops removed ones, so
    a running server can pick up freshly built fleet artifacts without a
    restart (the reference redeployed a pod per model instead).

    ``owned`` (multi-host serving mesh): an explicit member-ownership
    set — the collection loads and serves ONLY these names even when the
    artifact dir holds the whole fleet (a shared volume is the common
    deploy). ``None`` (the default) means unpartitioned: own everything
    on disk, exactly the old behavior. An owned-but-empty partition is
    legal (a small fleet over many replicas, or a source replica that
    migrated everything away) and does NOT raise at startup the way an
    empty unpartitioned dir does — the mesh routing plane, not this
    process, decides whether zero members here is a problem.
    """

    def __init__(
        self,
        root: str,
        target_name: Optional[str] = None,
        owned=None,
    ):
        self.root = root
        self.target_name = target_name
        self.owned = None if owned is None else set(owned)
        # (models, metadata) published together as ONE tuple: refresh()
        # builds fresh dicts off to the side and swaps them in with a
        # single (GIL-atomic) assignment, so readers on other threads
        # never see a half-mutated collection — published dicts are never
        # mutated afterwards. Read both sides through snapshot() when
        # cross-dict consistency matters.
        self._state: tuple = ({}, {})
        self._mtimes: Dict[str, float] = {}
        # operator-visible corrupt-artifact accounting: the healthy-subset
        # fallback below must not be invisible. ``load_failures`` is the
        # CURRENT failed set (latest scan); ``load_failed_total`` counts
        # every failed load attempt monotonically (each retrying refresh
        # increments it again — that is what a Prometheus counter wants,
        # rate() > 0 means "still failing")
        self.load_failures: Dict[str, str] = {}
        self.load_failed_total: int = 0
        changes = self.refresh()
        if not self.models and self.owned is None:
            detail = (
                f"; all artifact loads failed: {changes['failed']}"
                if changes["failed"]
                else ""
            )
            raise FileNotFoundError(
                f"No model artifacts found under {root!r}{detail}"
            )
        if changes["failed"]:
            # serve the healthy subset (one corrupt artifact must not
            # crashloop serving for the whole fleet) — but loudly: a
            # partial startup is an operator problem, not business as usual
            logger.error(
                "Startup loaded %d models but %d artifacts FAILED: %s",
                len(self.models), len(changes["failed"]), changes["failed"],
            )

    @property
    def models(self) -> Dict[str, Any]:
        return self._state[0]

    @property
    def metadata(self) -> Dict[str, Dict]:
        return self._state[1]

    def snapshot(self) -> tuple:
        """One consistent (models, metadata) pair."""
        return self._state

    def entry(self, name: str):
        """(model, metadata) read from ONE state snapshot — the two-dict
        lookup a concurrent refresh could otherwise straddle."""
        models, metadata = self._state
        return models[name], metadata.get(name, {})

    def _scan(self) -> Dict[str, str]:
        """name -> artifact dir for the current on-disk state, filtered
        to this collection's ownership set when one is active (the mesh
        partition: the shared volume holds everyone's artifacts, this
        replica loads only its own)."""
        on_disk = scan_artifacts(self.root, self.target_name)
        if self.owned is None:
            return on_disk
        return {n: p for n, p in on_disk.items() if n in self.owned}

    # ------------------------------------------------------------------ #
    # mesh ownership (multi-host serving): acquire/release move a member
    # between replicas; the bank rebuild + zero-downtime swap happens in
    # the caller (server/views.py mesh endpoints, under the reload lock)
    # ------------------------------------------------------------------ #

    def acquire(self, name: str) -> Dict[str, Any]:
        """Take ownership of ``name`` (its artifact must already be under
        ``root`` — the mesh acquire endpoint ships it first) and load it.
        Idempotent; on an unpartitioned collection ownership is implicit
        and this is just a refresh. Raises ``FileNotFoundError`` when the
        artifact is not on disk — taking ownership of nothing would
        blackhole the member's traffic behind a routing entry."""
        if self.owned is not None:
            self.owned.add(name)
        changes = self.refresh()
        if name not in self.models:
            if self.owned is not None:
                self.owned.discard(name)
            reason = changes["failed"].get(name, "artifact not found on disk")
            raise FileNotFoundError(
                f"cannot acquire {name!r} under {self.root!r}: {reason}"
            )
        return changes

    def release(self, name: str) -> Dict[str, Any]:
        """Drop ownership of ``name`` (the migration source's half of a
        cross-replica move): the member stops loading/serving here; its
        artifact stays on disk (cheap, and a failed migration can
        re-acquire without re-shipping). On an unpartitioned collection
        the current roster is materialized as the ownership set first —
        release must work on a replica that booted owning everything."""
        if name not in self.models:
            raise KeyError(f"cannot release unknown member {name!r}")
        if self.owned is None:
            self.owned = set(self.models)
        self.owned.discard(name)
        return self.refresh()

    def refresh(self) -> Dict[str, Any]:
        """Incremental rescan. Returns {"added": [...], "updated": [...],
        "removed": [...], "failed": {name: error}} by model name. Changes
        are staged on copies and published atomically (see ``_state``).

        Per-entry load isolation: a corrupt or mid-write artifact (a
        builder racing the reload is normal in a live fleet) must not
        block reloading everything else — the failing name is skipped
        (its previously loaded version, if any, keeps serving), reported
        under ``failed``, and its mtime stays unrecorded so the next
        refresh retries it."""
        on_disk = self._scan()
        models, metadata = dict(self.models), dict(self.metadata)
        # mtimes stage on a copy too: recording them eagerly would let a
        # load failure mark an ALREADY-RELOADED name as current while its
        # new model was discarded — serving the stale model forever after
        mtimes = dict(self._mtimes)
        added, updated, removed = [], [], []
        failed: Dict[str, str] = {}
        for name in list(models):
            if name not in on_disk:
                removed.append(name)
                del models[name]
                metadata.pop(name, None)
                mtimes.pop(name, None)
        for name, path in on_disk.items():
            try:
                mtime = os.path.getmtime(os.path.join(path, "model.pkl"))
            except OSError as exc:
                # deleted between _scan() and here (builder rewriting):
                # report it — a name silently in no bucket would hide a
                # stale-serving model from callers watching ``failed``
                failed[name] = f"{type(exc).__name__}: {exc}"
                continue
            is_new = name not in models
            if not is_new and mtime == mtimes.get(name):
                continue
            try:
                self._load_one(models, metadata, name, path)
            except Exception as exc:
                logger.warning("Failed to load %r from %s: %s", name, path, exc)
                failed[name] = f"{type(exc).__name__}: {exc}"
                continue
            mtimes[name] = mtime
            (added if is_new else updated).append(name)
        self._state = (models, metadata)  # atomic publish
        self._mtimes = mtimes
        self.load_failures = dict(failed)
        self.load_failed_total += len(failed)
        if added or updated or removed or failed:
            logger.info(
                "Collection refresh: +%d ~%d -%d !%d (now %d models)",
                len(added), len(updated), len(removed), len(failed), len(models),
            )
        return {
            "added": added, "updated": updated, "removed": removed,
            "failed": failed,
        }

    def publish(
        self, updates: Dict[str, Any], note: Optional[Dict[str, Any]] = None
    ) -> None:
        """Atomically publish in-memory model replacements (the streaming
        adaptation plane's recalibration/refit path — no artifact write).

        Only names already in the collection may be replaced: new members
        arrive via artifacts + :meth:`refresh`. The replacement persists
        across refreshes until the on-disk artifact's mtime changes (a
        rebuilt artifact is newer truth and wins). ``note`` (optional) is
        merged into each replaced member's metadata under
        ``online-adaptation`` so ``/metadata`` shows that — and when —
        the serving calibration diverged from the artifact."""
        unknown = [n for n in updates if n not in self.models]
        if unknown:
            raise KeyError(f"cannot publish unknown members: {sorted(unknown)}")
        models, metadata = dict(self.models), dict(self.metadata)
        for name, model in updates.items():
            models[name] = model
            if note is not None:
                meta = dict(metadata.get(name, {}))
                meta["online-adaptation"] = {
                    **note,
                    "total-anomaly-threshold": getattr(
                        model, "total_threshold_", None
                    ),
                    "threshold-method": getattr(model, "threshold_method_", None),
                }
                metadata[name] = meta
        self._state = (models, metadata)  # atomic publish

    def restore(self, state: tuple) -> None:
        """Roll back to a snapshot taken before :meth:`publish` (the
        adaptation plane's failed-swap path). The tuple is published
        as-is — snapshots are immutable by the ``_state`` contract."""
        self._state = state

    @staticmethod
    def _load_one(models: Dict, metadata: Dict, name: str, path: str) -> None:
        logger.info("Loading model %r from %s", name, path)
        _FP_LOAD.fire()
        # assign only after BOTH loads succeed: a metadata failure must
        # not leave a model without its metadata in the staged dicts
        model = serializer.load(path)
        meta = serializer.load_metadata(path)
        # serve the artifact's recorded name if present
        meta.setdefault("name", name)
        models[name] = model
        metadata[name] = meta

    def __contains__(self, name: str) -> bool:
        return name in self.models

    def __getitem__(self, name: str):
        return self.models[name]

    def names(self):
        return sorted(self.models)
