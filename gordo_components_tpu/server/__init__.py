"""Model server.

Reference parity: gordo_components/server/ (unverified; SURVEY.md §2
"server") — the reference runs one Flask+gunicorn process per model. The
TPU-native server is one aiohttp process serving a *collection* of models
(a fleet shard resident in a chip's HBM), with the same per-target REST
surface, so Ambassador-style routing by ``{target}`` still works.
"""

import asyncio
import contextlib
import itertools
import logging
import os
import sys
import time
from typing import Optional

from aiohttp import web

from gordo_components_tpu.observability import MetricsRegistry, Tracer
from gordo_components_tpu.observability.goodput import GoodputLedger
from gordo_components_tpu.observability.slo import SLOTracker
from gordo_components_tpu.observability.tracing import format_traceparent
from gordo_components_tpu.resilience import (
    QuarantineSet,
    configure_from_env,
    faultpoint,
)
from gordo_components_tpu.resilience.deadline import (
    DEADLINE_HEADER,
    Deadline,
    default_deadline_ms,
    parse_deadline_ms,
)
from gordo_components_tpu.server.bank import BatchingEngine, ModelBank
from gordo_components_tpu.server.model_io import ModelCollection
from gordo_components_tpu.server.stats import LatencyHistogram
from gordo_components_tpu.server.views import routes

logger = logging.getLogger(__name__)

# server-generated request-id sequence (used when the client sent none);
# process-wide so ids stay unique across app rebuilds in one process
_RID_SEQ = itertools.count(1)

# request-body size cap, shared with the worker pool (server/workers.py)
# so every accept path — primary, workers, UDS — enforces ONE limit
CLIENT_MAX_SIZE = 256 * 1024**2

# stats mutation guard for multi-worker serving: with workers=1 (the
# default) every mutation happens on one loop thread and stats["lock"]
# is absent — this shared nullcontext keeps that path allocation-free
# and lock-free. The worker pool (server/workers.py) installs a real
# threading.Lock so N worker loops can't lose counter increments.
_NO_LOCK = contextlib.nullcontext()


# transport-level chaos seam (mesh game days): armed with the
# connection-class fault kinds (refuse/reset/blackhole — resilience/
# faults.py), the middleware below ABORTS the raw socket instead of
# answering, so a real peer observes a real transport failure
# (ServerDisconnectedError / hang), not an in-band 500. Injection over
# subprocess boundaries rides GORDO_FAULTS, which build_app arms.
_FP_CONNECTION = faultpoint("server.connection")


@web.middleware
async def _chaos_transport_middleware(request, handler):
    """Outermost middleware: when ``server.connection`` fires, kill the
    TCP connection before any handler (or stats accounting) runs — the
    disarmed cost is one attribute read per request."""
    try:
        _FP_CONNECTION.fire()
    except asyncio.CancelledError:
        raise
    except BaseException:
        transport = request.transport
        if transport is not None:
            transport.abort()
        raise
    return await handler(request)


def _trace_headers(headers, rid: str, trace) -> None:
    """Stamp the id headers every response must carry: the gordo request
    id, the generic ``X-Request-Id`` (the trace id when traced, so an
    operator pastes it straight into ``GET /traces?id=``), and the W3C
    ``traceparent`` continuing the request's trace context downstream."""
    headers["X-Gordo-Request-Id"] = rid
    headers["X-Request-Id"] = trace.trace_id if trace is not None else rid
    if trace is not None:
        headers["traceparent"] = format_traceparent(
            trace.trace_id, trace.root.span_id
        )


@web.middleware
async def _stats_middleware(request, handler):
    """Per-endpoint-kind request/error counters + service-time histograms
    for ``GET .../stats``, plus request-id/trace propagation: the client's
    ``X-Gordo-Request-Id`` header (or a server-generated id) is stashed on
    the request, echoed on the response, and logged in the access line —
    so a latency-histogram outlier or an engine-batch failure is traceable
    back to one request. When the app carries a tracer
    (observability/tracing.py), a request-scoped trace opens here (W3C
    ``traceparent`` in, root span = endpoint kind), rides the request
    through the engine/bank stage spans, and closes with the response —
    its id echoed in ``X-Request-Id``/``traceparent`` and attached as an
    exemplar on the request-latency bucket it landed in, so a histogram
    spike resolves to one retrievable trace. Single event-loop thread:
    plain dict/int mutation is safe. Counter keys come from the matched
    route TEMPLATE (a bounded set) — keying on raw paths would let a
    scanner probing random URLs grow the dict without bound."""
    stats = request.app["stats"]
    resource = getattr(request.match_info.route, "resource", None)
    canonical = getattr(resource, "canonical", None)
    if canonical is None:
        kind = "other"  # unmatched route (404 scanners land here)
    elif canonical.endswith("/anomaly/prediction"):
        kind = "anomaly"
    else:
        kind = canonical.rsplit("/", 1)[-1] or "/"
    # multi-worker serving: stats["lock"] exists only when the worker
    # pool installed it (workers > 1) — the default path stays the
    # lock-free single-loop mutation it always was
    lock = stats.get("lock") or _NO_LOCK
    # which worker loop parsed this request (server/workers.py tags each
    # worker app); absent (None) outside pool mode — no per-worker
    # series render, the stability contract's default-off rule
    worker = getattr(request.app, "gordo_worker", None)
    with lock:
        stats["requests"][kind] = stats["requests"].get(kind, 0) + 1
        if worker is not None:
            w = stats["workers"]
            w[worker] = w.get(worker, 0) + 1
        if request.method == "POST" and kind in ("prediction", "anomaly", "ingest"):
            # per-encoding data-plane accounting (stability contract:
            # gordo_server_requests_total{encoding} + request_bytes_total):
            # which wire format the fleet's clients actually negotiate, and
            # the bytes each moves — the numbers the tensor-vs-JSON bench
            # legs and the bytes-per-row dashboards read. ONE classification
            # rule shared with the scoring handlers (utils/wire.py), so the
            # metrics can never disagree with the path a request took.
            from gordo_components_tpu.utils.wire import encoding_of

            enc = encoding_of(request.content_type)
            wire = stats["wire"]
            wire["requests"][enc] = wire["requests"].get(enc, 0) + 1
            wire["bytes"][enc] = (
                wire["bytes"].get(enc, 0) + (request.content_length or 0)
            )
        hist = stats["latency"].get(kind)
        if hist is None:
            hist = stats["latency"][kind] = LatencyHistogram()
    # bounded: a hostile header must not become an unbounded log/label blob
    rid = request.headers.get("X-Gordo-Request-Id", "")[:128] or (
        f"srv-{next(_RID_SEQ):x}"
    )
    request["request_id"] = rid
    # per-request time budget (resilience/deadline.py): the client's
    # X-Gordo-Deadline-Ms header, or the operator default
    # (GORDO_DEFAULT_DEADLINE_MS, resolved once at build_app). The
    # engine drops entries whose deadline passes before device dispatch
    # (504). No header + no default is the common case and costs one
    # dict read — held to the <=5% hotloop guard in tests/test_deadline.py
    raw_deadline = request.headers.get(DEADLINE_HEADER)
    deadline_ms = parse_deadline_ms(raw_deadline) if raw_deadline else None
    if deadline_ms is None:
        deadline_ms = request.app.get("default_deadline_ms")
    request["deadline"] = (
        Deadline.after_ms(deadline_ms) if deadline_ms else None
    )
    # QoS identity (qos/classify.py): headers here, possibly overridden
    # by the binary body's __meta__ sidecar in _parse_scoring — the
    # FINAL value on the request is what the ledger attributes below.
    # Untagged traffic gets the shared default instance (no allocation).
    if kind in ("prediction", "anomaly"):
        from gordo_components_tpu.qos.classify import classify_headers

        request["qos"] = classify_headers(request.headers)
    tracer = request.app.get("tracer")
    trace = None
    if tracer is not None:
        trace = tracer.start_trace(
            kind,
            traceparent=request.headers.get("traceparent"),
            request_id=rid,
        )
        if trace is not None:
            request["trace"] = trace
    t0 = time.monotonic()
    status = 500  # a non-HTTP handler crash surfaces as a 500
    counted = False
    try:
        resp = await handler(request)
        status = resp.status
    except web.HTTPException as exc:
        status = exc.status
        _trace_headers(exc.headers, rid, trace)
        if exc.status >= 400:
            with lock:
                stats["errors"] += 1
        raise
    except Exception:
        # a handler crash is a 500; the counter must see exactly the
        # failures an operator most needs to — and the response we build
        # here (instead of re-raising into aiohttp's default handler)
        # still carries the request-id echo, so the one request a client
        # most wants to trace is the one that stays traceable
        with lock:
            stats["errors"] += 1
        counted = True
        logger.exception(
            "unhandled error serving %s %s (rid=%s)",
            request.method, request.path, rid,
        )
        resp = web.json_response(
            {"error": "internal server error", "request_id": rid}, status=500
        )
    finally:
        # errored requests count too: a timeout-then-500 pattern is
        # exactly what a tail-latency histogram exists to surface
        elapsed = time.monotonic() - t0
        with lock:
            hist.record(elapsed)
        # goodput classification (observability/goodput.py): every
        # SCORING request commits its wall time + attributed device time
        # to the ledger with its final outcome — 504s are expired work,
        # other >=400s (and non-finite scores behind a 200) wasted work.
        # One dict read when disabled (GORDO_SLO=0 -> no ledger at all).
        # A cancellation (client disconnect, or a hedge win cancelling
        # the losing replica's request — PR 4's NORMAL operation) is not
        # a server failure: it must not classify as a 500 and burn the
        # availability budget, so it skips the ledger entirely.
        if kind in ("prediction", "anomaly") and not isinstance(
            sys.exc_info()[1], asyncio.CancelledError
        ):
            ledger = request.app.get("goodput")
            if ledger is not None:
                # per-class attribution: the tenant label is the
                # cardinality-BOUNDED one (known tenants + default +
                # "other") — stamped by admission when it ran, derived
                # here otherwise, never the raw header string
                qos = request.get("qos")
                tenant_label = request.get("qos_label")
                if qos is not None and tenant_label is None:
                    adm = request.app.get("qos_admission")
                    tenant_label = qos.label_tenant(
                        adm.known_tenants if adm is not None else None
                    )
                # under the pool, finish_request callers multiply (one
                # per worker loop) — the ledger's single-writer cell
                # contract is restored by the same stats lock
                with lock:
                    ledger.finish_request(
                        status=status,
                        elapsed_s=elapsed,
                        device_s=request.get("device_s", 0.0),
                        scores_finite=request.get("scores_finite", True),
                        tenant=tenant_label or "default",
                        qos_class=(
                            qos.qos_class if qos is not None else "interactive"
                        ),
                    )
        if trace is not None:
            trace.finish(error=status >= 400, status=status)
            # exemplar-style link on the latency histogram: the LAST trace
            # to land in each bucket, keyed by the bucket's le edge
            # (formatted EXACTLY as the Prometheus exposition formats it,
            # so the strings join against the scraped histogram) — bounded
            # at O(buckets) per kind, surfaced through /stats so "p99
            # spiked" resolves to "this trace" in two clicks. Only
            # RETAINED traces publish an exemplar: a head-sample drop must
            # not leave a dangling id the /traces lookup can't resolve
            if trace.retained:
                from gordo_components_tpu.observability.metrics import _fmt

                # _fmt renders inf as "+Inf", matching the bucket labels
                with lock:
                    stats.setdefault("exemplars", {}).setdefault(kind, {})[
                        _fmt(hist.bucket_le(elapsed))
                    ] = {
                        "trace_id": trace.trace_id,
                        "value_ms": round(elapsed * 1e3, 3),
                        "at": round(time.time(), 3),
                    }
        logger.debug(
            "access rid=%s trace=%s %s %s %d %.1fms",
            rid, trace.trace_id if trace is not None else "-",
            request.method, request.path, status, elapsed * 1e3,
        )
    _trace_headers(resp.headers, rid, trace)
    if not counted and resp.status >= 400:
        with lock:
            stats["errors"] += 1
    return resp


def _server_collector(app: web.Application):
    """Read-through exposition of the middleware's stats dict: the scrape
    endpoint reads the same integers /stats reports, so they cannot
    drift."""

    def collect():
        stats = app["stats"]
        yield (
            "gordo_server_uptime_seconds", "gauge",
            "Seconds since server start", {},
            time.time() - stats["started_at"],
        )
        for kind, n in stats["requests"].items():
            yield (
                "gordo_server_requests_total", "counter",
                "HTTP requests by endpoint kind", {"kind": kind}, n,
            )
        yield (
            "gordo_server_errors_total", "counter",
            "HTTP responses with status >= 400", {}, stats["errors"],
        )
        # the data plane by encoding (stability contract): scoring/ingest
        # POSTs and their body bytes, labeled json|parquet|tensor. NOTE
        # for aggregators: these share the requests_total family with the
        # {kind} samples, so a scoring POST appears under BOTH label
        # dimensions — sum() by one label, never over the whole family
        # (docs/observability.md spells this out)
        for enc, n in stats["wire"]["requests"].items():
            yield (
                "gordo_server_requests_total", "counter",
                "Scoring/ingest POSTs by wire encoding "
                "(second label dimension of requests_total)",
                {"encoding": enc}, n,
            )
        for enc, n in stats["wire"]["bytes"].items():
            yield (
                "gordo_server_request_bytes_total", "counter",
                "Scoring/ingest request body bytes by wire encoding",
                {"encoding": enc}, n,
            )
        # multi-worker accept-path balance (stability contract): which
        # worker loop parsed each request — a worker starving while the
        # others saturate is the SO_REUSEPORT/acceptor skew this series
        # exists to show. Absent (no samples) outside pool mode.
        for worker, n in sorted(stats.get("workers", {}).items()):
            yield (
                "gordo_server_worker_requests_total", "counter",
                "HTTP requests parsed per worker event loop",
                {"worker": worker}, n,
            )
        # local zero-copy transport counters (utils/shm_ring.py installs
        # the cell when GORDO_SHM_RING arms the ring; absent otherwise)
        shm = stats.get("shm")
        if shm is not None:
            yield (
                "gordo_shm_requests_total", "counter",
                "Scoring requests served over the shared-memory ring",
                {}, shm["requests"],
            )
            yield (
                "gordo_shm_errors_total", "counter",
                "Shared-memory ring requests answered with an error "
                "status", {}, shm["errors"],
            )
        for kind, hist in stats["latency"].items():
            yield (
                "gordo_server_request_seconds", "histogram",
                "Service time by endpoint kind", {"kind": kind}, hist,
            )
        collection = app.get("collection")
        if collection is not None:
            yield (
                "gordo_server_models", "gauge",
                "Models loaded in the collection", {},
                len(collection.models),
            )
            # corrupt-artifact visibility (the healthy-subset fallback
            # used to be invisible to operators): total failed load
            # attempts (counter; nonzero rate = an artifact is STILL
            # failing every refresh) + the current failed set's size
            yield (
                "gordo_models_load_failed_total", "counter",
                "Artifact load attempts that failed (corrupt/mid-write)",
                {}, collection.load_failed_total,
            )
            yield (
                "gordo_models_load_failed", "gauge",
                "Artifacts failing to load as of the latest scan", {},
                len(collection.load_failures),
            )
        quarantine = app.get("quarantine")
        if quarantine is not None:
            yield (
                "gordo_quarantined_models", "gauge",
                "Models evicted from routing by the scoring-failure "
                "breaker (410 until cleared)", {}, len(quarantine),
            )

    return collect


def _hbm_collector():
    """Device HBM usage as gauges, read fresh per scrape — the same
    numbers ``utils/profiling.device_memory_stats`` records into build
    metadata, republished live so memory headroom is scrapeable."""
    from gordo_components_tpu.utils.profiling import device_memory_stats

    def collect():
        for dev, st in device_memory_stats().items():
            for key in ("bytes_in_use", "bytes_limit", "peak_bytes_in_use"):
                if key in st:
                    yield (
                        f"gordo_device_hbm_{key}", "gauge",
                        "Per-device HBM memory (bytes)", {"device": dev},
                        st[key],
                    )

    return collect


def build_app(
    model_dir: str,
    target_name: Optional[str] = None,
    use_bank: Optional[bool] = None,
    bank_flush_ms: float = 2.0,
    bank_max_batch: int = 64,
    bank_max_queue: Optional[int] = None,
    devices: Optional[int] = None,
    quarantine_threshold: Optional[int] = None,
    bank_inflight: Optional[int] = None,
    arena_max_mb: Optional[float] = None,
    bank_dtype: Optional[str] = None,
    bank_kernel: Optional[str] = None,
    clock=None,
) -> web.Application:
    """App factory: loads the artifact(s) under ``model_dir`` once.

    When ``use_bank`` (default: env ``GORDO_SERVER_BANK`` != "0"), every
    bankable model is additionally stacked into an HBM-resident
    :class:`ModelBank` and requests are continuously batched through it;
    non-bankable models keep the per-model scoring path.

    ``devices`` (default: env ``GORDO_SERVER_DEVICES``; 0/unset = all
    available when >1, else single-device) shards the bank over a
    ``models``-axis mesh so a multi-chip server slice holds each model
    once and routes requests to the owning chip — the layout the
    generated manifests' ``server_devices`` request assumes.

    Hot-path pipeline knobs (docs/operations.md "Hot-path pipeline &
    tuning"): ``bank_inflight`` (env ``GORDO_BANK_INFLIGHT``) bounds how
    many bucket groups ``score_many`` keeps in flight on the device;
    ``arena_max_mb`` (env ``GORDO_ARENA_MAX_MB``) bounds the
    padded-buffer arena. ``GORDO_COMPILE_CACHE_DIR`` arms the persistent
    XLA compilation cache before the bank's bucket programs build, so a
    restarted replica re-warms from disk instead of recompiling.

    ``clock`` is the wall-time seam (replay/clock.py): the streaming
    plane's lateness/staleness accounting and the SLO tracker's window
    aging read it, so the replay harness can compress event time
    without distorting their semantics. Default (None) is the real
    clock — production never passes this.
    """
    def env_int(
        name: str, default: Optional[str] = None, hint: str = ""
    ) -> Optional[int]:
        """Integer env knob with an actionable error: these deploy to
        every replica, and a bare int() traceback would crashloop the
        fleet with no hint which knob is malformed."""
        raw = os.environ.get(name, default)
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError:
            raise ValueError(
                f"{name} must be an integer, got {raw!r}"
                + (f" ({hint})" if hint else "")
            ) from None

    # chaos/fault config: arms any GORDO_FAULTS sites before the first
    # artifact load / bucket compile can hit them; no-op when unset
    configure_from_env()
    # persistent XLA compilation cache (same knob the builder CLI wires):
    # armed BEFORE the bank compiles its bucket programs, so a restarted
    # or rolling-deployed replica loads them from the shared volume
    # instead of stalling its first requests on recompiles
    cache_dir = os.environ.get("GORDO_COMPILE_CACHE_DIR")
    if cache_dir:
        from gordo_components_tpu.utils.profiling import enable_compile_cache

        try:
            enable_compile_cache(cache_dir)
        except Exception:
            logger.warning(
                "GORDO_COMPILE_CACHE_DIR=%s: could not enable the "
                "persistent compilation cache; serving continues without it",
                cache_dir, exc_info=True,
            )
    if use_bank is None:
        use_bank = os.environ.get("GORDO_SERVER_BANK", "1") != "0"
    if devices is None:
        devices = env_int(
            "GORDO_SERVER_DEVICES", "0", hint="0/unset = all available devices"
        )
    mesh = None
    if use_bank and devices != 1:
        import jax

        from gordo_components_tpu.parallel.mesh import fleet_mesh

        avail = len(jax.devices())
        want = avail if devices in (0, -1) else min(devices, avail)
        if devices > avail:
            logger.warning(
                "GORDO_SERVER_DEVICES=%d but only %d device(s) present; "
                "sharding the bank over %d",
                devices, avail, want,
            )
        if want > 1:
            mesh = fleet_mesh(want)
    app = web.Application(
        client_max_size=CLIENT_MAX_SIZE,
        middlewares=[_chaos_transport_middleware, _stats_middleware],
    )
    # the wall-time seam: every component whose semantics are defined in
    # wall time (streaming lateness/staleness, SLO windows) reads THIS
    # clock, so replay can swap in a compressed timeline app-wide
    from gordo_components_tpu.replay.clock import SYSTEM_CLOCK

    app["clock"] = clock if clock is not None else SYSTEM_CLOCK
    app["stats"] = {
        "started_at": time.time(),
        "requests": {},
        "errors": 0,
        "latency": {},
        "exemplars": {},
        # per-encoding data-plane counters (json|parquet|tensor): scoring
        # /ingest POST counts + request body bytes, fed by the middleware
        "wire": {"requests": {}, "bytes": {}},
        # per-worker request counters (server/workers.py tags each worker
        # loop's app): empty — and emitting no series — outside pool mode
        "workers": {},
    }
    # operator default request budget (ms): applied by the middleware to
    # every request that carries no X-Gordo-Deadline-Ms header; None
    # (unset) keeps the pre-deadline behavior of never expiring
    app["default_deadline_ms"] = default_deadline_ms()
    # per-app request tracer (observability/tracing.py): the middleware
    # opens a trace per request, the engine/bank record stage spans into
    # it, and ``GET .../traces`` serves the ring + slow reservoir.
    # ``GORDO_TRACE_SAMPLE=0`` disables tracing entirely (start_trace
    # returns None and every call site skips on that one check)
    app["tracer"] = Tracer()
    # per-app metrics registry (observability/): the bank router and the
    # batching engine record per-shard/per-bucket series here; ``GET
    # .../metrics`` renders it as Prometheus text and ``GET .../stats``
    # embeds the same registry's JSON snapshot — one source, two views.
    # Per-app (not process-global) so test suites building many apps in
    # one process don't bleed series into each other.
    registry = MetricsRegistry()
    app["metrics"] = registry
    registry.collector(_server_collector(app), key="server")
    registry.collector(_hbm_collector(), key="hbm")
    # goodput ledger + SLO burn-rate tracker (observability/goodput.py,
    # observability/slo.py): the middleware classifies every scoring
    # request's outcome, the engine/bank feed stage + device-window
    # seconds, and GET .../slo serves the multi-window burn rates the
    # same registry renders as gordo_slo_burn_rate{objective,window}.
    # GORDO_SLO=0 disables the whole plane (no ledger object exists; the
    # call sites pay one None check — the hot-loop guard's contract)
    ledger = GoodputLedger.from_env(registry)
    app["goodput"] = ledger
    if ledger is not None:
        # SLO window ages ride the seam: under replay a "5m" burn
        # window spans 5 replayed minutes, not 5 real ones
        app["slo"] = SLOTracker(
            ledger, registry=registry, clock=app["clock"].monotonic
        )
    # multi-tenant QoS admission (qos/admission.py): per-tenant token
    # buckets + per-class shed thresholds in front of the engine, wired
    # to the SLO tracker's per-class fast-window burn so overload sheds
    # the class already burning budget fastest. Always constructed —
    # with no GORDO_QOS_TENANTS it is default-open and the scoring path
    # pays one depth comparison per request.
    from gordo_components_tpu.qos.admission import AdmissionController

    admission = AdmissionController.from_env()
    app["qos_admission"] = admission
    admission.install_collector(registry)
    slo_tracker = app.get("slo")
    if slo_tracker is not None and hasattr(slo_tracker, "class_burn"):
        admission.burn_for = slo_tracker.class_burn
    # access-heat accountant + device-cost attribution (observability/
    # heat.py, cost.py): heat is APP-level state — every bank generation
    # feeds the same accountant, so the decayed per-member history
    # survives /reload and rebalance swaps; cost joins the bank's static
    # FLOPs table to the ledger's measured device seconds on a sampling
    # cadence. GORDO_HEAT=0 / GORDO_COST=0 disable each plane (the
    # object is None; the bank pays one None check — the hot-loop
    # guard's contract). Both decay/sample on the replay-aware clock.
    from gordo_components_tpu.observability.cost import cost_from_env
    from gordo_components_tpu.observability.heat import heat_from_env

    app["heat"] = heat_from_env(registry, clock=app["clock"])
    app["cost"] = cost_from_env(
        ledger, lambda: app.get("bank"), registry=registry, clock=app["clock"]
    )
    # multi-host serving mesh (parallel/distributed.py): with
    # GORDO_MESH_REPLICA_ID/GORDO_MESH_REPLICAS set, this process is one
    # replica of a fleet mesh and loads ONLY its deterministic member
    # partition from the (typically shared) artifact dir — watchman's
    # routing table points clients at the owning replica, and the mesh
    # acquire/release endpoints (views.py) move members between replicas
    # live. Unset (the default): unpartitioned, zero new code runs.
    from gordo_components_tpu.parallel.distributed import bootstrap_serving_mesh
    from gordo_components_tpu.server.model_io import scan_artifacts

    mesh_identity = bootstrap_serving_mesh()
    owned = None
    if mesh_identity is not None:
        roster = sorted(scan_artifacts(model_dir, target_name))
        owned = mesh_identity.partition(roster)
        logger.info(
            "mesh replica %d/%d owns %d of %d member(s)",
            mesh_identity.replica_id, mesh_identity.replica_count,
            len(owned), len(roster),
        )
    app["mesh"] = mesh_identity
    # flight recorder (docs/observability.md "Flight recorder"): the
    # structured event log is ALWAYS on — state transitions are rare
    # (swaps, reloads, quarantines), so one locked deque append per
    # transition is noise and the timeline is there when the incident
    # hits. The metric history store is GORDO_HISTORY-gated and None
    # when off; call sites pay one `is None` check (the disabled
    # contract the hot-loop guard enforces).
    from gordo_components_tpu.observability.events import EventLog
    from gordo_components_tpu.observability.timeseries import history_from_env

    replica_name = (
        f"replica-{mesh_identity.replica_id}" if mesh_identity is not None else None
    )
    events = EventLog(clock=app["clock"], replica=replica_name)
    events.attach_registry(registry)
    app["events"] = events
    history = history_from_env(registry, clock=app["clock"])
    app["history"] = history
    if history is not None:

        async def _start_history_sampler(app: web.Application) -> None:
            store = app["history"]
            store.sample()  # boot baseline: rates start on the 2nd pass

            async def _tick():
                # cadence in seam seconds, like the SLO sampler: a replay
                # clock compresses the real sleep so samples land every
                # interval_s of REPLAYED time
                real_sleep = store.interval_s / max(1.0, app["clock"].timescale)
                while True:
                    await asyncio.sleep(real_sleep)
                    store.sample()

            app["history_sampler"] = asyncio.get_running_loop().create_task(_tick())

        async def _stop_history_sampler(app: web.Application) -> None:
            import contextlib

            task = app.get("history_sampler")
            if task is not None:
                task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await task

        app.on_startup.append(_start_history_sampler)
        app.on_cleanup.append(_stop_history_sampler)

    # fault fires land on the timeline (armed sites only — the disarmed
    # hot path never reaches the listener). Process-global seam, so the
    # most recently built app owns it; uninstall on cleanup only if it
    # is still ours (many short-lived apps per test process)
    from gordo_components_tpu.resilience.faults import set_fire_listener

    def _on_fault_fire(site: str, spec) -> None:
        events.emit(
            "fault.fired",
            severity="warning",
            generation=app.get("bank_generation"),
            site=site,
            fired=spec.fired,
        )

    async def _install_fault_listener(app: web.Application) -> None:
        set_fire_listener(_on_fault_fire)

    async def _uninstall_fault_listener(app: web.Application) -> None:
        from gordo_components_tpu.resilience import faults as _faults

        if _faults._FIRE_LISTENER is _on_fault_fire:
            set_fire_listener(None)

    app.on_startup.append(_install_fault_listener)
    app.on_cleanup.append(_uninstall_fault_listener)
    collection = ModelCollection(model_dir, target_name=target_name, owned=owned)
    app["collection"] = collection
    # per-model scoring-failure breaker (resilience/quarantine.py): a
    # model that keeps failing or emitting NaN is evicted from routing
    # (410 + reason) instead of crash-looping requests; /healthz reports
    # the tri-state (ok/degraded/unhealthy) over quarantine + load state
    if quarantine_threshold is None:
        from gordo_components_tpu.resilience.quarantine import DEFAULT_THRESHOLD

        quarantine_threshold = env_int(
            "GORDO_QUARANTINE_THRESHOLD",
            str(DEFAULT_THRESHOLD),
            hint="consecutive scoring failures before eviction; <=0 disables",
        )
    app["quarantine"] = QuarantineSet(threshold=quarantine_threshold)
    app["bank_enabled"] = use_bank
    if bank_max_queue is None and os.environ.get("GORDO_BANK_MAX_QUEUE"):
        # operator backpressure knob: how deep the scoring queue may grow
        # before requests shed with 429 (default 8 * max_batch)
        bank_max_queue = env_int("GORDO_BANK_MAX_QUEUE")
    app["bank_config"] = {
        "max_batch": bank_max_batch,
        "flush_ms": bank_flush_ms,
        "max_queue": bank_max_queue,
        # pipeline knobs, remembered so /reload rebuilds the bank with
        # the same window/arena budget the app booted with (None = the
        # env/default resolution inside ModelBank)
        "inflight": bank_inflight,
        "arena_max_mb": arena_max_mb,
        # precision/capacity knobs (docs/operations.md "Precision &
        # capacity tuning"): storage dtype for the stacked weights (env
        # GORDO_BANK_DTYPE) and the banked-epilogue dispatch mode (env
        # GORDO_BANK_KERNEL) — remembered so /reload rebuilds the bank
        # at the same precision the app booted with
        "bank_dtype": bank_dtype,
        "bank_kernel": bank_kernel,
    }
    app["bank_mesh"] = mesh  # reload (views.py) rebuilds under the same mesh
    if use_bank:
        bank = ModelBank.from_models(
            collection.models,
            mesh=mesh,
            registry=registry,
            inflight=bank_inflight,
            arena_max_mb=arena_max_mb,
            bank_dtype=bank_dtype,
            bank_kernel=bank_kernel,
            ledger=ledger,
            heat=app["heat"],
        )
        # expose the bank even when nothing banked: /models reports the
        # coverage (banked vs per-model fallback, with reasons)
        app["bank"] = bank
        # store the RESOLVED precision/kernel, not the requested (often
        # None) values: a /reload must rebuild at what the app actually
        # booted with, even if the env changed underneath it since
        app["bank_config"]["bank_dtype"] = bank.bank_dtype
        app["bank_config"]["bank_kernel"] = bank.kernel_mode
        # placement control plane (placement/): GET /placement and
        # POST /rebalance work in every mode; GORDO_REBALANCE=auto adds
        # the background evaluator. Generation 0 is the boot bank; every
        # applied swap (rebalance or /reload) bumps it.
        from gordo_components_tpu.placement.controller import (
            PlacementController,
        )

        app["bank_generation"] = 0
        app["placement"] = PlacementController(app)

        async def _start_placement(app: web.Application) -> None:
            app["placement"].start()

        async def _stop_placement(app: web.Application) -> None:
            await app["placement"].stop()

        app.on_startup.append(_start_placement)
        app.on_cleanup.append(_stop_placement)
        if len(bank):

            async def _start_engine(app: web.Application) -> None:
                engine = BatchingEngine(
                    bank,
                    max_batch=bank_max_batch,
                    flush_ms=bank_flush_ms,
                    max_queue=bank_max_queue,
                    # present only under the worker pool: serializes this
                    # engine's bank dispatches with the per-worker engines
                    dispatch_lock=app.get("bank_dispatch_lock"),
                )
                engine.start()
                app["bank_engine"] = engine
                # pre-compile scoring programs off the request path so the
                # first request doesn't pay the XLA compile — in the
                # BACKGROUND: awaiting here would hold the port closed for
                # the whole compile loop and fail readiness probes on
                # large fleets
                if os.environ.get("GORDO_SERVER_WARMUP", "1") != "0":
                    app["warmup_future"] = asyncio.get_running_loop().run_in_executor(
                        None, bank.warmup
                    )

            app.on_startup.append(_start_engine)

    # streaming ingestion & online adaptation plane (streaming/):
    # DEFAULT OFF (GORDO_STREAM=0) — the scoring hot path is untouched
    # and no gordo_stream_*/gordo_drift_* series appear (the contract
    # tests/test_streaming.py's hot-loop guard holds). When enabled, the
    # server accumulates fresh windows via POST .../{target}/ingest,
    # detects drift (GET .../drift), and recalibrates/refits through the
    # zero-downtime swap; GORDO_STREAM_ADAPT=auto arms the background loop
    if os.environ.get("GORDO_STREAM", "0") not in ("0", "", "false"):
        from gordo_components_tpu.streaming import StreamingPlane

        app["stream"] = StreamingPlane(app)

        async def _start_stream(app: web.Application) -> None:
            app["stream"].start()

        async def _stop_stream(app: web.Application) -> None:
            await app["stream"].stop()

        app.on_startup.append(_start_stream)
        app.on_cleanup.append(_stop_stream)

    if ledger is not None:
        # background SLO sampling cadence: the tracker also samples
        # lazily on reads, but a replica nobody is scraping must still
        # age its windows so the first scrape after an incident sees the
        # burn, not a flat line ending at the last visitor
        async def _start_slo_sampler(app: web.Application) -> None:
            tracker = app["slo"]
            tracker.sample(force=True)  # boot baseline sample

            async def _tick():
                # cadence in seam seconds: a replay clock compresses
                # the real sleep so samples land every
                # sample_interval_s of REPLAYED time
                real_sleep = tracker.sample_interval_s / max(
                    1.0, app["clock"].timescale
                )
                while True:
                    await asyncio.sleep(real_sleep)
                    tracker.sample()

            app["slo_sampler"] = asyncio.get_running_loop().create_task(_tick())

        app.on_startup.append(_start_slo_sampler)

        async def _stop_slo_sampler(app: web.Application) -> None:
            import contextlib

            task = app.get("slo_sampler")
            if task is not None:
                task.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await task

        app.on_cleanup.append(_stop_slo_sampler)

    async def _stop_engine(app: web.Application) -> None:
        engine = app.get("bank_engine")
        if engine is not None:
            await engine.stop()
        fut = app.get("warmup_future")
        if fut is not None and not fut.done():
            # executor jobs can't be interrupted; just don't tear the app
            # down from under a still-running compile
            import contextlib

            with contextlib.suppress(Exception):
                await fut

    app.on_cleanup.append(_stop_engine)
    app.add_routes(routes)
    return app


def run_server(
    model_dir: str,
    host: str = "0.0.0.0",
    port: int = 5555,
    target_name: Optional[str] = None,
    devices: Optional[int] = None,
    workers: Optional[int] = None,
    uds_path: Optional[str] = None,
    shm_ring: Optional[str] = None,
) -> None:
    """Blocking server entrypoint (reference: ``run_server`` /
    ``Dockerfile-ModelServer`` CMD).

    Saturation knobs (docs/operations.md "Saturating the serving
    plane"): ``workers`` / ``GORDO_SERVER_WORKERS`` runs N parse loops
    behind one accept path (server/workers.py); ``uds_path`` /
    ``GORDO_UDS`` adds a Unix-domain-socket listener speaking the same
    HTTP surface; ``shm_ring`` / ``GORDO_SHM_RING`` arms the
    shared-memory scoring ring for co-located producers
    (utils/shm_ring.py). All default OFF: with none set, this is the
    exact single-loop ``web.run_app`` serving it always was.
    """
    from gordo_components_tpu.server.workers import ServerPool, resolve_workers

    workers = resolve_workers(workers)
    if uds_path is None:
        uds_path = os.environ.get("GORDO_UDS") or None
    if shm_ring is None:
        shm_ring = os.environ.get("GORDO_SHM_RING") or None
    app = build_app(model_dir, target_name=target_name, devices=devices)
    logger.info(
        "Serving %d model(s) on %s:%d", len(app["collection"].models), host, port
    )
    if workers == 1 and not uds_path and not shm_ring:
        web.run_app(app, host=host, port=port)
        return
    pool = ServerPool(
        app, host=host, port=port, workers=workers,
        uds_path=uds_path, shm_ring=shm_ring,
    )
    pool.start()
    try:
        pool.wait()
    finally:
        pool.stop()


__all__ = ["build_app", "run_server", "ModelCollection", "ModelBank", "BatchingEngine"]
