"""Model server.

Reference parity: gordo_components/server/ (unverified; SURVEY.md §2
"server") — the reference runs one Flask+gunicorn process per model. The
TPU-native server is one aiohttp process serving a *collection* of models
(a fleet shard resident in a chip's HBM), with the same per-target REST
surface, so Ambassador-style routing by ``{target}`` still works.
"""

import asyncio
import logging
import os
import time
from typing import Optional

from aiohttp import web

from gordo_components_tpu.server.bank import BatchingEngine, ModelBank
from gordo_components_tpu.server.model_io import ModelCollection
from gordo_components_tpu.server.stats import LatencyHistogram
from gordo_components_tpu.server.views import routes

logger = logging.getLogger(__name__)


@web.middleware
async def _stats_middleware(request, handler):
    """Per-endpoint-kind request/error counters + service-time histograms
    for ``GET .../stats``. Single event-loop thread: plain dict/int
    mutation is safe. Counter keys come from the matched route TEMPLATE
    (a bounded set) — keying on raw paths would let a scanner probing
    random URLs grow the dict without bound."""
    stats = request.app["stats"]
    resource = getattr(request.match_info.route, "resource", None)
    canonical = getattr(resource, "canonical", None)
    if canonical is None:
        kind = "other"  # unmatched route (404 scanners land here)
    elif canonical.endswith("/anomaly/prediction"):
        kind = "anomaly"
    else:
        kind = canonical.rsplit("/", 1)[-1] or "/"
    stats["requests"][kind] = stats["requests"].get(kind, 0) + 1
    hist = stats["latency"].get(kind)
    if hist is None:
        hist = stats["latency"][kind] = LatencyHistogram()
    t0 = time.monotonic()
    try:
        resp = await handler(request)
    except web.HTTPException as exc:
        if exc.status >= 400:
            stats["errors"] += 1
        raise
    except Exception:
        # a handler crash becomes a 500 upstream; the counter must see
        # exactly the failures an operator most needs to
        stats["errors"] += 1
        raise
    finally:
        # errored requests count too: a timeout-then-500 pattern is
        # exactly what a tail-latency histogram exists to surface
        hist.record(time.monotonic() - t0)
    if resp.status >= 400:
        stats["errors"] += 1
    return resp


def build_app(
    model_dir: str,
    target_name: Optional[str] = None,
    use_bank: Optional[bool] = None,
    bank_flush_ms: float = 2.0,
    bank_max_batch: int = 64,
    bank_max_queue: Optional[int] = None,
    devices: Optional[int] = None,
) -> web.Application:
    """App factory: loads the artifact(s) under ``model_dir`` once.

    When ``use_bank`` (default: env ``GORDO_SERVER_BANK`` != "0"), every
    bankable model is additionally stacked into an HBM-resident
    :class:`ModelBank` and requests are continuously batched through it;
    non-bankable models keep the per-model scoring path.

    ``devices`` (default: env ``GORDO_SERVER_DEVICES``; 0/unset = all
    available when >1, else single-device) shards the bank over a
    ``models``-axis mesh so a multi-chip server slice holds each model
    once and routes requests to the owning chip — the layout the
    generated manifests' ``server_devices`` request assumes.
    """
    def env_int(
        name: str, default: Optional[str] = None, hint: str = ""
    ) -> Optional[int]:
        """Integer env knob with an actionable error: these deploy to
        every replica, and a bare int() traceback would crashloop the
        fleet with no hint which knob is malformed."""
        raw = os.environ.get(name, default)
        if raw is None:
            return None
        try:
            return int(raw)
        except ValueError:
            raise ValueError(
                f"{name} must be an integer, got {raw!r}"
                + (f" ({hint})" if hint else "")
            ) from None

    if use_bank is None:
        use_bank = os.environ.get("GORDO_SERVER_BANK", "1") != "0"
    if devices is None:
        devices = env_int(
            "GORDO_SERVER_DEVICES", "0", hint="0/unset = all available devices"
        )
    mesh = None
    if use_bank and devices != 1:
        import jax

        from gordo_components_tpu.parallel.mesh import fleet_mesh

        avail = len(jax.devices())
        want = avail if devices in (0, -1) else min(devices, avail)
        if devices > avail:
            logger.warning(
                "GORDO_SERVER_DEVICES=%d but only %d device(s) present; "
                "sharding the bank over %d",
                devices, avail, want,
            )
        if want > 1:
            mesh = fleet_mesh(want)
    app = web.Application(
        client_max_size=256 * 1024**2, middlewares=[_stats_middleware]
    )
    app["stats"] = {
        "started_at": time.time(),
        "requests": {},
        "errors": 0,
        "latency": {},
    }
    collection = ModelCollection(model_dir, target_name=target_name)
    app["collection"] = collection
    app["bank_enabled"] = use_bank
    if bank_max_queue is None and os.environ.get("GORDO_BANK_MAX_QUEUE"):
        # operator backpressure knob: how deep the scoring queue may grow
        # before requests shed with 429 (default 8 * max_batch)
        bank_max_queue = env_int("GORDO_BANK_MAX_QUEUE")
    app["bank_config"] = {
        "max_batch": bank_max_batch,
        "flush_ms": bank_flush_ms,
        "max_queue": bank_max_queue,
    }
    app["bank_mesh"] = mesh  # reload (views.py) rebuilds under the same mesh
    if use_bank:
        bank = ModelBank.from_models(collection.models, mesh=mesh)
        # expose the bank even when nothing banked: /models reports the
        # coverage (banked vs per-model fallback, with reasons)
        app["bank"] = bank
        if len(bank):

            async def _start_engine(app: web.Application) -> None:
                engine = BatchingEngine(
                    bank,
                    max_batch=bank_max_batch,
                    flush_ms=bank_flush_ms,
                    max_queue=bank_max_queue,
                )
                engine.start()
                app["bank_engine"] = engine
                # pre-compile scoring programs off the request path so the
                # first request doesn't pay the XLA compile — in the
                # BACKGROUND: awaiting here would hold the port closed for
                # the whole compile loop and fail readiness probes on
                # large fleets
                if os.environ.get("GORDO_SERVER_WARMUP", "1") != "0":
                    app["warmup_future"] = asyncio.get_running_loop().run_in_executor(
                        None, bank.warmup
                    )

            app.on_startup.append(_start_engine)

    async def _stop_engine(app: web.Application) -> None:
        engine = app.get("bank_engine")
        if engine is not None:
            await engine.stop()
        fut = app.get("warmup_future")
        if fut is not None and not fut.done():
            # executor jobs can't be interrupted; just don't tear the app
            # down from under a still-running compile
            import contextlib

            with contextlib.suppress(Exception):
                await fut

    app.on_cleanup.append(_stop_engine)
    app.add_routes(routes)
    return app


def run_server(
    model_dir: str,
    host: str = "0.0.0.0",
    port: int = 5555,
    target_name: Optional[str] = None,
    devices: Optional[int] = None,
) -> None:
    """Blocking server entrypoint (reference: ``run_server`` /
    ``Dockerfile-ModelServer`` CMD)."""
    app = build_app(model_dir, target_name=target_name, devices=devices)
    logger.info(
        "Serving %d model(s) on %s:%d", len(app["collection"].models), host, port
    )
    web.run_app(app, host=host, port=port)


__all__ = ["build_app", "run_server", "ModelCollection", "ModelBank", "BatchingEngine"]
