"""Model server.

Reference parity: gordo_components/server/ (unverified; SURVEY.md §2
"server") — the reference runs one Flask+gunicorn process per model. The
TPU-native server is one aiohttp process serving a *collection* of models
(a fleet shard resident in a chip's HBM), with the same per-target REST
surface, so Ambassador-style routing by ``{target}`` still works.
"""

import asyncio
import logging
from typing import Optional

from aiohttp import web

from gordo_components_tpu.server.model_io import ModelCollection
from gordo_components_tpu.server.views import routes

logger = logging.getLogger(__name__)


def build_app(model_dir: str, target_name: Optional[str] = None) -> web.Application:
    """App factory: loads the artifact(s) under ``model_dir`` once."""
    app = web.Application(client_max_size=256 * 1024**2)
    app["collection"] = ModelCollection(model_dir, target_name=target_name)
    app.add_routes(routes)
    return app


def run_server(
    model_dir: str,
    host: str = "0.0.0.0",
    port: int = 5555,
    target_name: Optional[str] = None,
) -> None:
    """Blocking server entrypoint (reference: ``run_server`` /
    ``Dockerfile-ModelServer`` CMD)."""
    app = build_app(model_dir, target_name=target_name)
    logger.info(
        "Serving %d model(s) on %s:%d", len(app["collection"].models), host, port
    )
    web.run_app(app, host=host, port=port)


__all__ = ["build_app", "run_server", "ModelCollection"]
