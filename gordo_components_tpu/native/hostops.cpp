// Native host-side data-pipeline ops for the fleet engine.
//
// The reference's host hot loop is per-tag IO + pandas joins inside one
// builder pod (SURVEY.md §3.1). The TPU-native fleet engine replaces the
// per-pod loop with one process feeding a whole model bank, which moves the
// bottleneck to host-side staging: stacking/padding thousands of ragged
// member arrays into the (M, rows, features) device layout, and
// materializing lookback windows for sequence models. Both are pure
// memcpy-shaped loops — this library runs them multithreaded (OpenMP) in
// C++ instead of a Python for-loop, with gordo_components_tpu/native/
// __init__.py falling back to numpy when no toolchain is available.
//
// Build: g++ -O3 -march=native -fopenmp -shared -fPIC hostops.cpp
// ABI: plain C, int64 sizes, float32 row-major buffers (numpy defaults).

#include <cstdint>
#include <cstring>

extern "C" {

// Stack n_members ragged (rows[i], n_features) arrays into a padded
// (M, padded_rows, n_features) block plus an (M, padded_rows) sample mask.
// Slots i >= n_members replicate member i % n_members (mesh-padding
// dummies, exactly like the Python path). out_x/out_mask must be
// zero-initialized by the caller (calloc'd numpy arrays).
void fleet_stack_pad(const float** members,
                     const int64_t* rows,
                     int64_t n_members,
                     int64_t M,
                     int64_t padded_rows,
                     int64_t n_features,
                     float* out_x,
                     float* out_mask) {
#pragma omp parallel for schedule(dynamic)
  for (int64_t i = 0; i < M; ++i) {
    const int64_t src = i % n_members;
    const int64_t r = rows[src];
    std::memcpy(out_x + i * padded_rows * n_features,
                members[src],
                sizeof(float) * static_cast<size_t>(r) * n_features);
    float* mask_row = out_mask + i * padded_rows;
    for (int64_t j = 0; j < r; ++j) mask_row[j] = 1.0f;
  }
}

// (rows, f) -> (rows - lookback + 1, lookback, f) sliding windows.
void sliding_windows_f32(const float* x,
                         int64_t rows,
                         int64_t f,
                         int64_t lookback,
                         float* out) {
  const int64_t nw = rows - lookback + 1;
  if (nw <= 0) return;
#pragma omp parallel for schedule(static)
  for (int64_t w = 0; w < nw; ++w) {
    std::memcpy(out + w * lookback * f,
                x + w * f,
                sizeof(float) * static_cast<size_t>(lookback) * f);
  }
}

}  // extern "C"
