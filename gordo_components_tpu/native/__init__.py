"""ctypes bindings for the native host-ops library (hostops.cpp).

Build-on-first-use: the shared library is compiled with the system ``g++``
into a per-source-hash cache path, so editing the .cpp transparently
rebuilds and stale caches are never loaded. Every entry point has a
numpy fallback with identical semantics — environments without a
toolchain (or with ``GORDO_NO_NATIVE=1``) lose only speed, never
functionality. The functional tests run both paths against each other.
"""

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from typing import List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "hostops.cpp")
_lib = None
_lib_tried = False


_CFLAGS = ["-O3", "-march=native", "-fopenmp", "-shared", "-fPIC"]


def _host_tag() -> str:
    """CPU identity for the cache key: -march=native binaries must not be
    shared across heterogeneous hosts (e.g. an NFS home on a cluster)."""
    import platform

    ident = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    ident += line
                    break
    except OSError:
        pass
    return hashlib.sha256(ident.encode()).hexdigest()[:8]


def _build_lib() -> Optional[ctypes.CDLL]:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src + " ".join(_CFLAGS).encode()).hexdigest()[:16]
    cache_dir = os.environ.get(
        "GORDO_NATIVE_CACHE",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "gordo-components-tpu",
        ),
    )
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"hostops-{tag}-{_host_tag()}.so")
    if not os.path.exists(so_path):
        # build to a temp name INSIDE cache_dir: os.replace must not cross
        # filesystems (tmpfs /tmp -> EXDEV)
        fd, tmp_so = tempfile.mkstemp(suffix=".so.tmp", dir=cache_dir)
        os.close(fd)
        try:
            cmd = ["g++", *_CFLAGS, _SRC, "-o", tmp_so]
            try:
                subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            except (OSError, subprocess.SubprocessError) as exc:
                logger.info("Native hostops build unavailable (%s); numpy path", exc)
                return None
            os.replace(tmp_so, so_path)  # atomic publish, same filesystem
        finally:
            if os.path.exists(tmp_so):
                os.unlink(tmp_so)
        logger.info("Built native hostops -> %s", so_path)
    lib = ctypes.CDLL(so_path)
    i64 = ctypes.c_int64
    fp = ctypes.POINTER(ctypes.c_float)
    lib.fleet_stack_pad.argtypes = [
        ctypes.POINTER(fp), ctypes.POINTER(i64), i64, i64, i64, i64, fp, fp,
    ]
    lib.fleet_stack_pad.restype = None
    lib.sliding_windows_f32.argtypes = [fp, i64, i64, i64, fp]
    lib.sliding_windows_f32.restype = None
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, or None (no toolchain / disabled)."""
    global _lib, _lib_tried
    if not _lib_tried:
        _lib_tried = True
        if os.environ.get("GORDO_NO_NATIVE") == "1":
            logger.info("Native hostops disabled via GORDO_NO_NATIVE")
        else:
            try:
                _lib = _build_lib()
            except Exception:
                logger.warning("Native hostops unavailable", exc_info=True)
                _lib = None
    return _lib


def _use_native() -> bool:
    """Native wins by parallelizing memcpy across cores; on a single-core
    host numpy's vectorized block ops are at parity or better (measured
    0.8-0.9x), so prefer numpy there. GORDO_FORCE_NATIVE=1 overrides for
    testing the native path on any host."""
    if os.environ.get("GORDO_FORCE_NATIVE") == "1":
        return get_lib() is not None
    return (os.cpu_count() or 1) > 1 and get_lib() is not None


def native_available() -> bool:
    return get_lib() is not None


# --------------------------------------------------------------------- #
# ops
# --------------------------------------------------------------------- #


def _as_c_f32(a: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.float32)


def fleet_stack_pad(
    members: List[np.ndarray], M: int, padded_rows: int, n_features: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Stack ragged (rows_i, n_features) members into a zero-padded
    (M, padded_rows, n_features) block + (M, padded_rows) mask; slots
    beyond len(members) replicate members cyclically (mesh padding)."""
    n = len(members)
    if n == 0:
        raise ValueError("No members to stack")
    cmembers = [_as_c_f32(m) for m in members]
    # validate on BOTH paths — the fallback must reject exactly what the
    # native code rejects, never silently broadcast a malformed member
    for m in cmembers:
        if m.ndim != 2 or m.shape[1] != n_features or m.shape[0] > padded_rows:
            raise ValueError(f"Bad member shape {m.shape} for ({padded_rows}, {n_features})")
    lib = get_lib() if _use_native() else None
    if lib is None:
        Xs = np.zeros((M, padded_rows, n_features), dtype=np.float32)
        mask = np.zeros((M, padded_rows), dtype=np.float32)
        for i in range(M):
            X = cmembers[i % n]
            Xs[i, : X.shape[0]] = X
            mask[i, : X.shape[0]] = 1.0
        return Xs, mask
    Xs = np.zeros((M, padded_rows, n_features), dtype=np.float32)
    mask = np.zeros((M, padded_rows), dtype=np.float32)
    fp = ctypes.POINTER(ctypes.c_float)
    ptrs = (fp * n)(*[m.ctypes.data_as(fp) for m in cmembers])
    rows = np.asarray([m.shape[0] for m in cmembers], dtype=np.int64)
    lib.fleet_stack_pad(
        ptrs,
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(n),
        ctypes.c_int64(M),
        ctypes.c_int64(padded_rows),
        ctypes.c_int64(n_features),
        Xs.ctypes.data_as(fp),
        mask.ctypes.data_as(fp),
    )
    return Xs, mask


def sliding_windows_host(X: np.ndarray, lookback: int) -> np.ndarray:
    """(rows, f) -> (rows - lookback + 1, lookback, f), float32."""
    X = _as_c_f32(X)
    rows, f = X.shape
    nw = rows - lookback + 1
    if nw <= 0:
        return np.zeros((0, lookback, f), dtype=np.float32)
    lib = get_lib() if _use_native() else None
    if lib is None:
        idx = np.arange(nw)[:, None] + np.arange(lookback)[None, :]
        return X[idx]
    out = np.empty((nw, lookback, f), dtype=np.float32)
    fp = ctypes.POINTER(ctypes.c_float)
    lib.sliding_windows_f32(
        X.ctypes.data_as(fp),
        ctypes.c_int64(rows),
        ctypes.c_int64(f),
        ctypes.c_int64(lookback),
        out.ctypes.data_as(fp),
    )
    return out
