"""Config-definition language: dotted import paths + nested kwargs.

Reference parity: ``pipeline_from_definition`` /
``pipeline_into_definition`` (gordo_components/serializer/, unverified;
SURVEY.md §2). A definition is:

- a string dotted path -> instantiate with defaults
  (``sklearn.preprocessing.MinMaxScaler``)
- a one-key dict ``{dotted.path: {kwargs}}`` -> instantiate with kwargs,
  recursively resolving kwarg values that are themselves definitions
- a list -> each element resolved (used for ``Pipeline(steps=...)`` and
  ``FeatureUnion(transformer_list=...)``)

``sklearn.pipeline.Pipeline`` steps and ``FeatureUnion`` transformer lists
accept bare definitions and are auto-named ``step_0..`` exactly so the
round-trip ``into_definition(from_definition(d)) == d``-modulo-names holds.
"""

import importlib
import inspect
import logging
from typing import Any, Dict, List, Union

logger = logging.getLogger(__name__)

# Reference-era dotted paths -> this package. Any other `gordo_components.`
# prefix falls back to a prefix rewrite.
_PATH_ALIASES = {
    "gordo_components.model.models.KerasAutoEncoder": "gordo_components_tpu.models.AutoEncoder",
    "gordo_components.model.models.KerasLSTMAutoEncoder": "gordo_components_tpu.models.LSTMAutoEncoder",
    "gordo_components.model.models.KerasLSTMForecast": "gordo_components_tpu.models.LSTMForecast",
    "gordo_components.model.anomaly.DiffBasedAnomalyDetector": "gordo_components_tpu.models.DiffBasedAnomalyDetector",
    "gordo_components.model.anomaly.diff.DiffBasedAnomalyDetector": "gordo_components_tpu.models.DiffBasedAnomalyDetector",
}


def import_locate(path: str) -> Any:
    """Import an object from a dotted path, applying reference aliases."""
    path = _PATH_ALIASES.get(path, path)
    if path.startswith("gordo_components."):
        path = "gordo_components_tpu." + path[len("gordo_components.") :]
    module_path, _, name = path.rpartition(".")
    if not module_path:
        raise ImportError(f"Not a dotted path: {path!r}")
    try:
        module = importlib.import_module(module_path)
        return getattr(module, name)
    except AttributeError:
        # maybe the "module" part is itself a class (nested attr)
        parent = import_locate(module_path)
        return getattr(parent, name)


def _looks_like_path(key: Any) -> bool:
    return isinstance(key, str) and "." in key


def from_definition(definition: Union[str, Dict, List]) -> Any:
    """Instantiate an object (usually an sklearn Pipeline) from a definition."""
    if isinstance(definition, str):
        if _looks_like_path(definition):
            return import_locate(definition)()
        raise ValueError(f"Cannot interpret definition string: {definition!r}")

    if isinstance(definition, list):
        return [from_definition(d) if _is_definition(d) else d for d in definition]

    if isinstance(definition, dict):
        if len(definition) != 1:
            raise ValueError(
                f"Definition dict must have exactly one dotted-path key, got {sorted(definition)}"
            )
        (path, kwargs), = definition.items()
        cls = import_locate(path)
        kwargs = dict(kwargs or {})
        kwargs = {k: _resolve_value(k, v) for k, v in kwargs.items()}
        return cls(**kwargs)

    raise ValueError(f"Cannot interpret definition of type {type(definition)}")


def _is_definition(v: Any) -> bool:
    if isinstance(v, str) and _looks_like_path(v):
        try:
            import_locate(v)
            return True
        except Exception:
            return False
    if isinstance(v, dict) and len(v) == 1:
        key = next(iter(v))
        if _looks_like_path(key):
            try:
                import_locate(key)
                return True
            except Exception:
                return False
    return False


def _resolve_value(key: str, value: Any) -> Any:
    # steps / transformer_list entries may be bare definitions or
    # (name, definition) pairs; auto-name bare entries
    if key in ("steps", "transformer_list") and isinstance(value, list):
        out = []
        for i, entry in enumerate(value):
            if isinstance(entry, (list, tuple)) and len(entry) == 2 and isinstance(entry[0], str) and not _is_definition(entry[0]):
                out.append((entry[0], from_definition(entry[1]) if _is_definition(entry[1]) else entry[1]))
            elif _is_definition(entry):
                obj = from_definition(entry)
                out.append((f"step_{i}", obj))
            else:
                out.append(entry)
        return out
    if _is_definition(value):
        return from_definition(value)
    if isinstance(value, list):
        return [from_definition(v) if _is_definition(v) else v for v in value]
    return value


# ---------------------------------------------------------------------- #
# inverse: object -> definition
# ---------------------------------------------------------------------- #


def _dotted_path(obj: Any) -> str:
    cls = type(obj)
    return f"{cls.__module__}.{cls.__qualname__}"


def _ctor_defaults(obj: Any) -> Dict[str, Any]:
    try:
        sig = inspect.signature(type(obj).__init__)
        return {
            k: p.default
            for k, p in sig.parameters.items()
            if p.default is not inspect.Parameter.empty
        }
    except (TypeError, ValueError):
        return {}


def into_definition(obj: Any, prune_defaults: bool = True) -> Union[str, Dict]:
    """Serialize an object back into the definition language.

    Uses ``capture_args``-captured params when present (our classes),
    otherwise sklearn's ``get_params(deep=False)`` pruned to non-default
    values so emitted configs stay human-sized.
    """
    path = _dotted_path(obj)

    if hasattr(obj, "_params"):
        params = dict(obj._params)
    elif hasattr(obj, "get_params"):
        params = obj.get_params(deep=False)
        if prune_defaults:
            defaults = _ctor_defaults(obj)
            params = {
                k: v
                for k, v in params.items()
                if not (k in defaults and _safe_eq(defaults[k], v))
            }
    else:
        params = {}

    params = {k: _encode_value(v) for k, v in params.items()}
    if not params:
        return path
    return {path: params}


def _safe_eq(a, b) -> bool:
    try:
        return bool(a == b)
    except Exception:
        return False


def _encode_value(v: Any) -> Any:
    # (name, estimator) tuples from Pipeline.steps / FeatureUnion
    if isinstance(v, tuple) and len(v) == 2 and isinstance(v[0], str) and hasattr(v[1], "get_params"):
        return [v[0], into_definition(v[1])]
    if isinstance(v, (list, tuple)):
        return [_encode_value(x) for x in v]
    if hasattr(v, "get_params") or hasattr(v, "_params"):
        return into_definition(v)
    return v


# Reference-era function names
pipeline_from_definition = from_definition
pipeline_into_definition = into_definition
