"""Artifact persistence: model directory trees.

Reference parity: serializer ``dump``/``load``/``load_metadata``
(gordo_components/serializer/, unverified; SURVEY.md §2) — the reference
persists a pipeline as a directory of pickled steps + Keras HDF5 +
``metadata.json``. Here the artifact directory is:

- ``model.pkl``      — the full (sklearn-compatible) object; our estimators
                       carry numpy param pytrees so plain pickle is exact
- ``params.npz``     — flax params flattened to ``a/b/c`` keys, saved
                       language-neutrally for non-Python consumers
- ``metadata.json``  — the build-metadata contract

The unit of persistence is the *finished model artifact* exactly as in the
reference (SURVEY.md §5 "Checkpoint/resume"); mid-training checkpointing of
fleet state lives in parallel/ (orbax), not here.
"""

import json
import os
import pickle
from typing import Any, Dict, Optional, Tuple

import numpy as np

_MODEL_FILE = "model.pkl"
_PARAMS_FILE = "params.npz"
_METADATA_FILE = "metadata.json"


def _flatten_params(tree: Any, prefix: str = "") -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_params(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten_params(v, f"{prefix}{i}/"))
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def dump(obj: Any, dest_dir: str, metadata: Optional[Dict] = None) -> None:
    """Persist a model (pipeline/estimator/detector) into ``dest_dir``."""
    os.makedirs(dest_dir, exist_ok=True)
    with open(os.path.join(dest_dir, _MODEL_FILE), "wb") as f:
        pickle.dump(obj, f)

    params = _extract_params(obj)
    if params:
        np.savez(os.path.join(dest_dir, _PARAMS_FILE), **params)

    if metadata is not None:
        with open(os.path.join(dest_dir, _METADATA_FILE), "w") as f:
            json.dump(metadata, f, default=str, indent=2)


def _extract_params(obj: Any) -> Dict[str, np.ndarray]:
    """Find flax param pytrees on the object (estimator, pipeline step, or
    anomaly wrapper) for the language-neutral npz."""
    if getattr(obj, "params_", None) is not None:
        return _flatten_params(obj.params_)
    if hasattr(obj, "base_estimator"):
        return _extract_params(obj.base_estimator)
    if hasattr(obj, "steps"):
        return _extract_params(obj.steps[-1][1])
    return {}


def dumps(obj: Any) -> bytes:
    return pickle.dumps(obj)


def loads(data: bytes) -> Any:
    return pickle.loads(data)


def load(source_dir: str) -> Any:
    with open(os.path.join(source_dir, _MODEL_FILE), "rb") as f:
        return pickle.load(f)


def load_metadata(source_dir: str) -> Dict:
    path = os.path.join(source_dir, _METADATA_FILE)
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        return json.load(f)
