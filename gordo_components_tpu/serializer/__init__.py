"""Serializer: bidirectional config ⇄ object, and artifact dump/load.

Reference parity: gordo_components/serializer/ (unverified; SURVEY.md §2
"serializer") — the pipeline-definition language (dotted import paths with
nested kwargs) is user-facing API in the reference and preserved here
verbatim, including reference-era ``gordo_components.*`` paths, which are
transparently aliased onto this package so existing fleet configs load
unchanged.
"""

from gordo_components_tpu.serializer.definitions import (
    from_definition,
    into_definition,
    pipeline_from_definition,
    pipeline_into_definition,
)
from gordo_components_tpu.serializer.artifacts import (
    dump,
    dumps,
    load,
    loads,
    load_metadata,
)

__all__ = [
    "from_definition",
    "into_definition",
    "pipeline_from_definition",
    "pipeline_into_definition",
    "dump",
    "dumps",
    "load",
    "loads",
    "load_metadata",
]
