"""Bulk prediction client.

Reference parity: ``Client`` (gordo_components/client/client.py, unverified;
SURVEY.md §2 "client", §3.3): discover the project's endpoints (watchman or
the server's collection listing), rebuild each machine's dataset config from
metadata, chunk the requested time range, POST batches with bounded
concurrency (the THROUGHPUT HOT LOOP), and optionally forward results to a
prediction store.
"""

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import aiohttp
import pandas as pd

from gordo_components_tpu.client.io import fetch_json
from gordo_components_tpu.dataset import get_dataset
from gordo_components_tpu.server.utils import dict_to_frame

logger = logging.getLogger(__name__)


@dataclass
class PredictionResult:
    """Per-machine outcome of a bulk run (reference: ``PredictionResult``)."""

    name: str
    predictions: Optional[pd.DataFrame]
    error_messages: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.predictions is not None and not self.error_messages


class Client:
    """Score time ranges against every model of a project."""

    def __init__(
        self,
        project: str,
        host: str = "localhost",
        port: int = 5555,
        scheme: str = "http",
        *,
        base_url: Optional[str] = None,
        batch_size: int = 1000,
        parallelism: int = 10,
        forwarder=None,
        use_anomaly: bool = True,
        metadata_fallback_dataset: Optional[Dict[str, Any]] = None,
    ):
        self.project = project
        self.base_url = base_url or f"{scheme}://{host}:{port}"
        self.batch_size = int(batch_size)
        self.parallelism = int(parallelism)
        self.forwarder = forwarder
        self.use_anomaly = use_anomaly
        self.metadata_fallback_dataset = metadata_fallback_dataset

    # ------------------------------------------------------------------ #

    def _url(self, target: str, endpoint: str) -> str:
        return f"{self.base_url}/gordo/v0/{self.project}/{target}/{endpoint}"

    async def _get_targets(self, session) -> List[str]:
        body = await fetch_json(
            session, f"{self.base_url}/gordo/v0/{self.project}/models"
        )
        return body["models"]

    async def _get_metadata(self, session, target: str) -> Dict[str, Any]:
        body = await fetch_json(session, self._url(target, "metadata"))
        return body.get("endpoint-metadata", {})

    def _dataset_config_from_metadata(self, meta, start, end) -> Dict[str, Any]:
        ds_meta = meta.get("dataset", {})
        config = self.metadata_fallback_dataset or {"type": "RandomDataset"}
        if ds_meta:
            # Tag dicts ({name, asset}) pass through whole: dropping asset
            # would break providers with asset-scoped layouts; row_filter and
            # aggregation must match training or scored rows diverge from
            # what the model saw.
            config = {
                "type": ds_meta.get("type", "TimeSeriesDataset"),
                "tag_list": ds_meta.get("tag_list", []),
                "resolution": ds_meta.get("resolution", "10min"),
                "aggregation_method": ds_meta.get("aggregation_method", "mean"),
                "row_filter": ds_meta.get("row_filter", ""),
                "data_provider": ds_meta.get("data_provider"),
            }
            if ds_meta.get("target_tag_list"):
                config["target_tag_list"] = ds_meta["target_tag_list"]
            if not isinstance(config["data_provider"], dict):
                # only a provider dict can be re-instantiated by the
                # dataset layer; a repr string cannot
                config.pop("data_provider", None)
        return {
            **config,
            "train_start_date": str(start),
            "train_end_date": str(end),
        }

    # ------------------------------------------------------------------ #

    def predict(
        self, start: pd.Timestamp, end: pd.Timestamp, targets: Optional[List[str]] = None
    ) -> List[PredictionResult]:
        """Synchronous entrypoint (reference CLI semantics)."""
        return asyncio.run(self.predict_async(start, end, targets))

    async def predict_async(
        self, start, end, targets: Optional[List[str]] = None
    ) -> List[PredictionResult]:
        timeout = aiohttp.ClientTimeout(total=600)
        sem = asyncio.Semaphore(self.parallelism)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            if targets is None:
                targets = await self._get_targets(session)
            results = await asyncio.gather(
                *(
                    self._predict_single(session, sem, t, start, end)
                    for t in targets
                )
            )
        if self.forwarder is not None:
            for result in results:
                if result.ok:
                    self.forwarder.forward(result)
        return list(results)

    async def _predict_single(
        self, session, sem, target: str, start, end
    ) -> PredictionResult:
        try:
            meta = await self._get_metadata(session, target)
            config = self._dataset_config_from_metadata(meta, start, end)
            dataset = get_dataset(config)
            X, y = await asyncio.get_running_loop().run_in_executor(
                None, dataset.get_data
            )
        except Exception as exc:
            logger.exception("Failed to build dataset for %s", target)
            return PredictionResult(target, None, [f"dataset: {exc}"])

        endpoint = "anomaly/prediction" if self.use_anomaly else "prediction"
        frames: List[pd.DataFrame] = []
        errors: List[str] = []

        async def post_chunk(chunk: pd.DataFrame):
            payload = {
                "X": chunk.values.tolist(),
                "index": [str(i) for i in chunk.index],
            }
            async with sem:
                try:
                    body = await fetch_json(
                        session,
                        self._url(target, endpoint),
                        method="POST",
                        json_payload=payload,
                    )
                except Exception as exc:
                    errors.append(f"chunk {chunk.index[0]}: {exc}")
                    return None
                return body

        chunks = [
            X.iloc[i : i + self.batch_size]
            for i in range(0, len(X), self.batch_size)
        ]
        bodies = await asyncio.gather(*(post_chunk(c) for c in chunks))
        for body in bodies:
            if body is None:
                continue
            if "data" in body and isinstance(body["data"], dict):
                frames.append(dict_to_frame(body))
            elif "data" in body:
                df = pd.DataFrame(body["data"])
                if body.get("index") and len(body["index"]) == len(df):
                    df.index = pd.to_datetime(body["index"], utc=True)
                frames.append(df)
        predictions = pd.concat(frames) if frames else None
        return PredictionResult(target, predictions, errors)
