"""Bulk prediction client.

Reference parity: ``Client`` (gordo_components/client/client.py, unverified;
SURVEY.md §2 "client", §3.3): discover the project's endpoints (watchman or
the server's collection listing), rebuild each machine's dataset config from
metadata, chunk the requested time range, POST batches with bounded
concurrency (the THROUGHPUT HOT LOOP), and optionally forward results to a
prediction store.
"""

import asyncio
import hashlib
import itertools
import logging
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import aiohttp
import pandas as pd

from gordo_components_tpu.client.io import fetch_json, fetch_metadata_all
from gordo_components_tpu.observability.tracing import format_traceparent
from gordo_components_tpu.dataset import get_dataset
from gordo_components_tpu.server.utils import dict_to_frame
from gordo_components_tpu.utils import parquet_engine_available

logger = logging.getLogger(__name__)

# below this many targets, per-target /metadata GETs beat downloading the
# whole fleet's metadata in one metadata-all response
_PREFETCH_MIN_TARGETS = 8


@dataclass
class PredictionResult:
    """Per-machine outcome of a bulk run (reference: ``PredictionResult``)."""

    name: str
    predictions: Optional[pd.DataFrame]
    error_messages: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.predictions is not None and not self.error_messages


class Client:
    """Score time ranges against every model of a project."""

    def __init__(
        self,
        project: str,
        host: str = "localhost",
        port: int = 5555,
        scheme: str = "http",
        *,
        base_url: Optional[str] = None,
        batch_size: int = 1000,
        parallelism: int = 10,
        forwarder=None,
        use_anomaly: bool = True,
        metadata_fallback_dataset: Optional[Dict[str, Any]] = None,
        use_parquet="auto",
    ):
        self.project = project
        self.base_url = base_url or f"{scheme}://{host}:{port}"
        self.batch_size = int(batch_size)
        self.parallelism = int(parallelism)
        self.forwarder = forwarder
        self.use_anomaly = use_anomaly
        self.metadata_fallback_dataset = metadata_fallback_dataset
        # request-body encoding for scoring POSTs: "auto" upgrades to
        # parquet when the server advertises it (JSON float-list
        # encode/decode dominates at fleet-backfill scale — the reference's
        # client used parquet for the same reason); True forces parquet,
        # False forces JSON. A mid-run parquet rejection (foreign server)
        # downgrades the rest of an "auto" run to JSON. Normalized here so
        # truthy non-True values (1, "yes") can't get auto-mode downgrade
        # semantics while claiming forced mode.
        if use_parquet not in (True, False, "auto"):
            raise ValueError(
                f"use_parquet must be True, False or 'auto', got {use_parquet!r}"
            )
        self.use_parquet = use_parquet
        self._parquet_active = False
        self._metadata_all: Dict[str, Any] = {}
        # request-id propagation: every scoring POST carries a unique
        # X-Gordo-Request-Id the server threads through its access log and
        # engine queue, so a slow/failed chunk in a fleet backfill is
        # traceable end to end (client log line <-> server histogram entry)
        self._rid_prefix = uuid.uuid4().hex[:12]
        self._rid_seq = itertools.count(1)

    def _next_request_id(self) -> str:
        return f"{self._rid_prefix}-{next(self._rid_seq):x}"

    @staticmethod
    def _trace_headers(rid: str) -> Dict[str, str]:
        """Scoring-POST id headers: the gordo request id plus a W3C
        ``traceparent`` whose trace id is DERIVED from the request id
        (md5 — identity, not security), so a client log line and the
        server-side trace are the same identifier family and either one
        recovers the other. The sampled flag is set: a request the
        client bothered to stamp is one the operator wants retrievable
        at ``GET .../traces`` regardless of server head sampling."""
        trace_id = hashlib.md5(rid.encode()).hexdigest()
        return {
            "X-Gordo-Request-Id": rid,
            "traceparent": format_traceparent(trace_id, trace_id[:16]),
        }

    # ------------------------------------------------------------------ #

    def _url(self, target: str, endpoint: str) -> str:
        return f"{self.base_url}/gordo/v0/{self.project}/{target}/{endpoint}"

    async def _get_metadata(self, session, target: str) -> Dict[str, Any]:
        meta = self._metadata_all.get(target)
        if meta is not None:
            return meta
        body = await fetch_json(session, self._url(target, "metadata"))
        return body.get("endpoint-metadata", {})

    async def _prefetch_metadata(self, session) -> None:
        """Prefetch every target's metadata in ONE request via the
        collection server's batched control-plane endpoint — at fleet
        scale the per-target ``/metadata`` round-trips otherwise cost N
        requests before any scoring starts. Best-effort with a short
        deadline and shape validation (shared helper, client/io.py):
        foreign servers keep the per-target path."""
        body = await fetch_metadata_all(session, self.base_url, self.project)
        if body is None:
            return
        self._metadata_all = {
            name: entry["endpoint-metadata"]
            for name, entry in body["targets"].items()
            if isinstance(entry, dict) and "endpoint-metadata" in entry
        }

    def _dataset_config_from_metadata(self, meta, start, end) -> Dict[str, Any]:
        ds_meta = meta.get("dataset", {})
        config = self.metadata_fallback_dataset or {"type": "RandomDataset"}
        if ds_meta:
            # Tag dicts ({name, asset}) pass through whole: dropping asset
            # would break providers with asset-scoped layouts; row_filter and
            # aggregation must match training or scored rows diverge from
            # what the model saw.
            config = {
                "type": ds_meta.get("type", "TimeSeriesDataset"),
                "tag_list": ds_meta.get("tag_list", []),
                "resolution": ds_meta.get("resolution", "10min"),
                "aggregation_method": ds_meta.get("aggregation_method", "mean"),
                "row_filter": ds_meta.get("row_filter", ""),
                "data_provider": ds_meta.get("data_provider"),
            }
            if ds_meta.get("target_tag_list"):
                config["target_tag_list"] = ds_meta["target_tag_list"]
            if not isinstance(config["data_provider"], dict):
                # only a provider dict can be re-instantiated by the
                # dataset layer; a repr string cannot
                config.pop("data_provider", None)
        return {
            **config,
            "train_start_date": str(start),
            "train_end_date": str(end),
        }

    # ------------------------------------------------------------------ #

    def predict(
        self, start: pd.Timestamp, end: pd.Timestamp, targets: Optional[List[str]] = None
    ) -> List[PredictionResult]:
        """Synchronous entrypoint (reference CLI semantics)."""
        return asyncio.run(self.predict_async(start, end, targets))

    async def predict_async(
        self, start, end, targets: Optional[List[str]] = None
    ) -> List[PredictionResult]:
        timeout = aiohttp.ClientTimeout(total=600)
        sem = asyncio.Semaphore(self.parallelism)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            models_body = None
            if targets is None or self.use_parquet == "auto":
                try:
                    models_body = await fetch_json(
                        session, f"{self.base_url}/gordo/v0/{self.project}/models"
                    )
                except Exception:
                    if targets is None:  # discovery is mandatory
                        raise
                    models_body = None  # encoding probe is best-effort
            if targets is None:
                targets = models_body["models"]
            # fresh per run: stale cached metadata must never outlive a
            # server-side /reload (a failed re-prefetch then falls back to
            # per-target fetches, not to last run's cache)
            self._metadata_all = {}
            if len(targets) >= _PREFETCH_MIN_TARGETS:
                # below that, per-target GETs are cheaper than pulling the
                # whole fleet's metadata for a handful of lookups
                await self._prefetch_metadata(session)
            if self.use_parquet == "auto":
                self._parquet_active = parquet_engine_available() and any(
                    "parquet" in a
                    for a in (models_body or {}).get("accepts", [])
                )
            else:
                self._parquet_active = bool(self.use_parquet)
                if self._parquet_active and not parquet_engine_available():
                    # forced mode fails loudly up front, not one opaque
                    # to_parquet ImportError per chunk
                    raise ImportError(
                        "use_parquet=True but no parquet engine "
                        "(pyarrow/fastparquet) is installed"
                    )
            results = await asyncio.gather(
                *(
                    self._predict_single(session, sem, t, start, end)
                    for t in targets
                )
            )
        if self.forwarder is not None:
            for result in results:
                if result.ok:
                    self.forwarder.forward(result)
        return list(results)

    async def _post_parquet(
        self, session, target, endpoint, chunk: pd.DataFrame,
        chunk_y: Optional[pd.DataFrame] = None,
        request_id: Optional[str] = None,
    ):
        """POST one chunk as a parquet body (index rides inside the file,
        so timestamps round-trip without the JSON string lists). Target
        columns for supervised machines are embedded under a ``__y__``
        prefix; the server splits them back out (server/utils.py)."""
        import io

        frame = chunk
        if chunk_y is not None:
            # indices are identical by construction (iloc slices of the
            # same row range), so this is a pure column concat
            frame = pd.concat([chunk, chunk_y.add_prefix("__y__")], axis=1)
        buf = io.BytesIO()
        frame.to_parquet(buf)
        headers = {"Content-Type": "application/x-parquet"}
        if request_id:
            headers.update(self._trace_headers(request_id))
        return await fetch_json(
            session,
            self._url(target, endpoint),
            method="POST",
            data=buf.getvalue(),
            headers=headers,
        )

    async def _predict_single(
        self, session, sem, target: str, start, end
    ) -> PredictionResult:
        try:
            meta = await self._get_metadata(session, target)
            config = self._dataset_config_from_metadata(meta, start, end)
            dataset = get_dataset(config)
            X, y = await asyncio.get_running_loop().run_in_executor(
                None, dataset.get_data
            )
        except Exception as exc:
            logger.exception("Failed to build dataset for %s", target)
            return PredictionResult(target, None, [f"dataset: {exc}"])

        endpoint = "anomaly/prediction" if self.use_anomaly else "prediction"
        frames: List[pd.DataFrame] = []
        errors: List[str] = []

        async def post_chunk(chunk: pd.DataFrame, chunk_y: Optional[pd.DataFrame]):
            async with sem:
                # one id per chunk, reused across the parquet->JSON
                # downgrade re-post: both attempts are the SAME request
                rid = self._next_request_id()
                parquet_exc = None
                if self._parquet_active:
                    try:
                        return await self._post_parquet(
                            session, target, endpoint, chunk, chunk_y,
                            request_id=rid,
                        )
                    except ValueError as exc:
                        # 4xx on the parquet body. Ambiguous: the server
                        # may reject the ENCODING (foreign pod, no parse
                        # engine) or this chunk may hit a genuine model
                        # error that would 400 under any encoding. The
                        # JSON re-post below disambiguates; forced mode
                        # never downgrades (documented contract).
                        if self.use_parquet is True:
                            errors.append(f"chunk {chunk.index[0]} (rid={rid}): {exc}")
                            return None
                        parquet_exc = exc
                    except Exception as exc:
                        errors.append(f"chunk {chunk.index[0]} (rid={rid}): {exc}")
                        return None
                payload = {
                    "X": chunk.values.tolist(),
                    "index": [str(i) for i in chunk.index],
                }
                if chunk_y is not None:
                    payload["y"] = chunk_y.values.tolist()
                try:
                    body = await fetch_json(
                        session,
                        self._url(target, endpoint),
                        method="POST",
                        json_payload=payload,
                        headers=self._trace_headers(rid),
                    )
                except Exception as exc:
                    errors.append(f"chunk {chunk.index[0]} (rid={rid}): {exc}")
                    return None
                if parquet_exc is not None:
                    # JSON succeeded where parquet 4xx'd: an encoding
                    # problem, not a model error — downgrade the rest of
                    # the run (a model error would have failed both and
                    # must NOT cost the whole fleet its parquet win)
                    logger.warning(
                        "parquet body rejected (%s) but JSON succeeded; "
                        "downgrading run to JSON", parquet_exc,
                    )
                    self._parquet_active = False
                return body

        # y rides along for supervised machines (target_tag_list): the
        # anomaly diff must be computed against the TRAINED target, not
        # X->X — silently dropping y here would score the wrong objective
        chunks = [
            (
                X.iloc[i : i + self.batch_size],
                None if y is None else y.iloc[i : i + self.batch_size],
            )
            for i in range(0, len(X), self.batch_size)
        ]
        bodies = await asyncio.gather(*(post_chunk(cx, cy) for cx, cy in chunks))
        for body in bodies:
            if body is None:
                continue
            if "data" in body and isinstance(body["data"], dict):
                frames.append(dict_to_frame(body))
            elif "data" in body:
                df = pd.DataFrame(body["data"])
                if body.get("index") and len(body["index"]) == len(df):
                    df.index = pd.to_datetime(body["index"], utc=True)
                frames.append(df)
        predictions = pd.concat(frames) if frames else None
        return PredictionResult(target, predictions, errors)
