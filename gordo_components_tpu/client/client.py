"""Bulk prediction client.

Reference parity: ``Client`` (gordo_components/client/client.py, unverified;
SURVEY.md §2 "client", §3.3): discover the project's endpoints (watchman or
the server's collection listing), rebuild each machine's dataset config from
metadata, chunk the requested time range, POST batches with bounded
concurrency (the THROUGHPUT HOT LOOP), and optionally forward results to a
prediction store.
"""

import asyncio
import contextlib
import functools
import hashlib
import itertools
import json
import logging
import random
import time
import uuid
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import aiohttp
import numpy as np
import pandas as pd

from gordo_components_tpu.client.io import (
    fetch_json,
    fetch_json_hedged,
    fetch_metadata_all,
)
from gordo_components_tpu.observability import get_registry
from gordo_components_tpu.observability.tracing import format_traceparent
from gordo_components_tpu.dataset import get_dataset
from gordo_components_tpu.resilience.deadline import Deadline, DeadlineExceeded
from gordo_components_tpu.resilience.retry_budget import RetryBudget
from gordo_components_tpu.server.utils import dict_to_frame
from gordo_components_tpu.utils import parquet_engine_available
from gordo_components_tpu.utils.encoding import parquet_engine
from gordo_components_tpu.utils.wire import (
    ANOMALY_FRAME_NAMES,
    TENSOR_CONTENT_TYPE,
    pack_frames,
    unpack_frames,
)

logger = logging.getLogger(__name__)

# below this many targets, per-target /metadata GETs beat downloading the
# whole fleet's metadata in one metadata-all response
_PREFETCH_MIN_TARGETS = 8

# latency samples needed before the hedge delay switches from the
# configured initial value to the observed p95
_HEDGE_MIN_SAMPLES = 16


class _LatencyTracker:
    """Bounded record of observed chunk latencies; p95 drives the hedge
    delay so only the slowest ~5% of requests ever pay a duplicate."""

    def __init__(self, maxlen: int = 256):
        self._samples: "deque[float]" = deque(maxlen=maxlen)

    def record(self, seconds: float) -> None:
        self._samples.append(seconds)

    def __len__(self) -> int:
        return len(self._samples)

    def p95(self) -> Optional[float]:
        if not self._samples:
            return None
        ordered = sorted(self._samples)
        return ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]


@dataclass
class PredictionResult:
    """Per-machine outcome of a bulk run (reference: ``PredictionResult``)."""

    name: str
    predictions: Optional[pd.DataFrame]
    error_messages: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.predictions is not None and not self.error_messages


class Client:
    """Score time ranges against every model of a project."""

    def __init__(
        self,
        project: str,
        host: str = "localhost",
        port: int = 5555,
        scheme: str = "http",
        *,
        base_url: Optional[str] = None,
        batch_size: int = 1000,
        parallelism: int = 10,
        forwarder=None,
        use_anomaly: bool = True,
        metadata_fallback_dataset: Optional[Dict[str, Any]] = None,
        use_parquet="auto",
        use_tensor="auto",
        transport: str = "auto",
        uds_path: Optional[str] = None,
        shm_ring: Optional[str] = None,
        retries: int = 3,
        backoff: float = 0.5,
        retry_budget: Optional[RetryBudget] = None,
        retry_budget_ratio: Optional[float] = None,
        deadline_ms: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[str] = None,
        hedge: bool = False,
        replica_urls: Optional[List[str]] = None,
        hedge_delay_init_s: float = 1.0,
        routing_url: Optional[str] = None,
        routing: Optional[Dict[str, Any]] = None,
        routing_refresh_window_s: float = 5.0,
    ):
        self.project = project
        # normalized (no trailing slash) so the hedge target exclusion
        # compares like with like against replica_urls below
        self.base_url = (base_url or f"{scheme}://{host}:{port}").rstrip("/")
        self.batch_size = int(batch_size)
        self.parallelism = int(parallelism)
        self.forwarder = forwarder
        self.use_anomaly = use_anomaly
        self.metadata_fallback_dataset = metadata_fallback_dataset
        # multi-tenant QoS identity (qos/classify.py): stamped on every
        # scoring POST as X-Gordo-Tenant / X-Gordo-Priority and, on the
        # binary/shm paths, in the __meta__ tensor sidecar — proxies may
        # strip custom headers and shm envelopes never had any. The
        # class also picks the client's own overload posture below
        # (retry ratio, hedging): a best-effort client must not amplify
        # the very overload that is shedding it.
        from gordo_components_tpu.qos.classify import (
            normalize_class,
            normalize_tenant,
        )

        self.tenant = normalize_tenant(tenant) if tenant else None
        self.qos_class = (
            normalize_class(priority) if priority else "interactive"
        )
        # transport citizenship knobs (previously hardcoded in io.py):
        # bounded retries with decorrelated-jitter backoff, all gated by
        # ONE shared token-bucket retry budget — a thousand chunks
        # failing together can re-offer at most ~ratio x the offered
        # load, not 3x (the synchronized-retry overload recipe)
        self.retries = int(retries)
        self.backoff = float(backoff)
        if retry_budget_ratio is None:
            # per-class retry appetite: lower classes re-offer less of
            # their failed load — they are the first to be shed, so
            # their retries are the likeliest to be pure overload fuel
            retry_budget_ratio = {
                "batch": 0.05, "best_effort": 0.02
            }.get(self.qos_class, 0.1)
        self.retry_budget = (
            retry_budget
            if retry_budget is not None
            else RetryBudget(ratio=retry_budget_ratio)
        )
        # per-chunk time budget (ms), stamped on every scoring POST as
        # X-Gordo-Deadline-Ms so a saturated server drops the work once
        # this client has given up; also bounds the dataset build
        self.deadline_ms = None if deadline_ms is None else float(deadline_ms)
        # tail-latency hedging: after a p95-derived delay, re-issue a
        # slow chunk POST to one other replica (from watchman's target
        # list — see replicas_from_watchman) and take the first success.
        # best_effort NEVER hedges: a hedge is a second copy of the load
        # the fleet is most willing to shed, and tail latency is not
        # part of that class's contract anyway.
        self.hedge = bool(hedge) and self.qos_class != "best_effort"
        self.replica_urls = [
            u.rstrip("/") for u in (replica_urls or []) if u.rstrip("/")
        ]
        self.hedge_delay_init_s = float(hedge_delay_init_s)
        self._latency = _LatencyTracker()
        self._hedge_stats: Dict[str, int] = {"hedges": 0, "hedge_wins": 0}
        self._hedge_rng = random.Random()
        # partition-aware fan-out (multi-host serving mesh): with a
        # routing table — fetched from watchman's GET /routing when
        # ``routing_url`` names the watchman base, or passed verbatim as
        # ``routing`` — every member's chunks POST to the replica that
        # OWNS it instead of one base URL, and hedges/fallbacks skip
        # replicas the table marks degraded/unreachable (or that
        # quarantine the member). Neither set: classic single-URL client,
        # zero new code on the chunk path.
        self.routing_url = (routing_url or "").rstrip("/") or None
        self._routing: Optional[Dict[str, Any]] = None
        self._routing_etag: Optional[str] = None
        if routing is not None:
            self._install_routing(routing)
        self._fanout_stats: Dict[str, int] = {
            "routed_chunks": 0, "routing_refreshes": 0, "reroutes": 0,
            "refreshes_throttled": 0,
        }
        # stale-table forced-refresh rate limit: ONE forced /routing
        # refetch per member per window. During a migration storm with a
        # dead replica, every chunk of every displaced member would
        # otherwise force its own refresh — a refresh stampede against
        # watchman exactly when it is busiest. Throttled attempts keep
        # their 404 (the bounded-retry contract is per-window, not gone).
        self.routing_refresh_window_s = float(routing_refresh_window_s)
        self._forced_refresh_at: Dict[str, float] = {}
        # request-body encoding for scoring POSTs: "auto" upgrades to
        # parquet when the server advertises it (JSON float-list
        # encode/decode dominates at fleet-backfill scale — the reference's
        # client used parquet for the same reason); True forces parquet,
        # False forces JSON. A mid-run parquet rejection (foreign server)
        # downgrades the rest of an "auto" run to JSON. Normalized here so
        # truthy non-True values (1, "yes") can't get auto-mode downgrade
        # semantics while claiming forced mode.
        if use_parquet not in (True, False, "auto"):
            raise ValueError(
                f"use_parquet must be True, False or 'auto', got {use_parquet!r}"
            )
        self.use_parquet = use_parquet
        self._parquet_active = False
        # framed binary tensor bodies (utils/wire.py) — the preferred
        # encoding when the server advertises application/x-gordo-tensor:
        # it upgrades BOTH wire directions (request rows and the 4x-larger
        # anomaly response), where parquet only ever covered the request.
        # Same negotiation contract as parquet: "auto" upgrades on the
        # advertisement and downgrades for the rest of the run when a
        # foreign server rejects a tensor body that JSON then accepts.
        if use_tensor not in (True, False, "auto"):
            raise ValueError(
                f"use_tensor must be True, False or 'auto', got {use_tensor!r}"
            )
        self.use_tensor = use_tensor
        self._tensor_active = False
        # local zero-copy transport negotiation (server/workers.py +
        # utils/shm_ring.py): "auto" climbs the ladder shm > uds > tcp
        # using the server's /models ``transports`` advertisement, each
        # rung verified LOCALLY (shm attachable, socket path present)
        # before use — a remote server's advertisement never breaks a
        # remote client, it just resolves to tcp. Explicit "uds"/"shm"
        # try exactly that rung and degrade to tcp with a warning
        # (graceful fallback); "tcp" is the classic path untouched.
        if transport not in ("auto", "tcp", "uds", "shm"):
            raise ValueError(
                f"transport must be auto|tcp|uds|shm, got {transport!r}"
            )
        self.transport = transport
        self.uds_path = uds_path
        self.shm_ring = shm_ring
        # resolved per run (predict_async): which rung actually carried
        # the scoring chunks — bench/demo report this next to rows/s
        self.transport_used = "tcp"
        self._shm_client = None
        self._data_session = None  # UDS session for scoring POSTs
        # sessions retired mid-run by _drop_uds: closed at run end, not
        # at retirement — sibling chunks may still have requests in
        # flight on them, and an immediate close would turn their clean
        # ClientConnectionError into an unhandled "Session is closed"
        self._dead_sessions: List[Any] = []
        # per-encoding wire accounting (bench's bytes-per-row legs +
        # gordo_client_request_bytes_total): body bytes out and rows
        # posted for every scoring POST that got a 2xx back
        self._wire_stats: Dict[str, Dict[str, int]] = {}
        self._metadata_all: Dict[str, Any] = {}
        # request-id propagation: every scoring POST carries a unique
        # X-Gordo-Request-Id the server threads through its access log and
        # engine queue, so a slow/failed chunk in a fleet backfill is
        # traceable end to end (client log line <-> server histogram entry)
        self._rid_prefix = uuid.uuid4().hex[:12]
        self._rid_seq = itertools.count(1)
        # streaming-forwarder accounting (ingest_async): rows accepted by
        # the server's window buffers, exposed as
        # gordo_client_ingest_rows_total through the collector below
        self._ingest_stats: Dict[str, int] = {"rows": 0, "chunks": 0}
        # after _rid_prefix: the metric series are labeled by it
        self._register_metrics()

    def _next_request_id(self) -> str:
        return f"{self._rid_prefix}-{next(self._rid_seq):x}"

    def _register_metrics(self) -> None:
        """Read-through exposition of the client's overload-citizenship
        counters in the process registry (the same cells bench snapshots
        into BENCH_DETAIL.json). Weakref: the process registry must not
        pin a discarded client. Series are labeled by the client's rid
        prefix and registered under a per-instance key, so two clients
        in one process (one per project, or a fresh client per run)
        neither replace each other's collectors nor emit colliding
        unlabeled samples; a discarded client's collector yields
        nothing through the dead weakref."""
        import weakref

        ref = weakref.ref(self)
        labels = {"client": self._rid_prefix}

        def collect():
            c = ref()
            if c is None:
                return
            b = c.retry_budget.snapshot()
            yield (
                "gordo_client_retries_total", "counter",
                "Retries the shared budget admitted", labels,
                b["retries_allowed"],
            )
            yield (
                "gordo_client_retries_denied_total", "counter",
                "Retries refused because the budget was exhausted "
                "(failed fast instead of re-offering load)", labels,
                b["retries_denied"],
            )
            yield (
                "gordo_client_retry_budget_tokens", "gauge",
                "Retry tokens currently banked", labels, b["tokens"],
            )
            yield (
                "gordo_client_hedges_total", "counter",
                "Hedge requests issued (primary slower than the hedge "
                "delay)", labels, c._hedge_stats["hedges"],
            )
            yield (
                "gordo_client_hedge_wins_total", "counter",
                "Hedged requests answered by the hedge replica first",
                labels, c._hedge_stats["hedge_wins"],
            )
            yield (
                "gordo_client_ingest_rows_total", "counter",
                "Stream rows the ingestion forwarder posted and the "
                "server accepted", labels, c._ingest_stats["rows"],
            )
            yield (
                "gordo_client_routed_chunks_total", "counter",
                "Scoring chunks routed to their member's owning replica "
                "via the mesh routing table", labels,
                c._fanout_stats["routed_chunks"],
            )
            yield (
                "gordo_client_routing_refreshes_total", "counter",
                "Routing-table fetches that installed a new table "
                "(200s; 304 not-modified polls excluded)", labels,
                c._fanout_stats["routing_refreshes"],
            )
            yield (
                "gordo_client_reroutes_total", "counter",
                "Chunks re-posted after a stale-table 404 forced a "
                "routing refresh", labels, c._fanout_stats["reroutes"],
            )
            yield (
                "gordo_client_routing_refreshes_throttled_total", "counter",
                "Forced stale-table refreshes suppressed by the "
                "per-member rate limit (refresh-stampede guard)",
                labels, c._fanout_stats["refreshes_throttled"],
            )
            for enc, st in list(c._wire_stats.items()):
                yield (
                    "gordo_client_request_bytes_total", "counter",
                    "Scoring request body bytes posted, by wire encoding",
                    {**labels, "encoding": enc}, st["bytes_out"],
                )

        get_registry().collector(collect, key=f"bulk_client:{self._rid_prefix}")

    def _note_wire(self, encoding: str, bytes_out: int, rows: int) -> None:
        """Count a successfully posted scoring chunk against its wire
        encoding (single event-loop thread: plain dict mutation)."""
        st = self._wire_stats.setdefault(
            encoding, {"posts": 0, "bytes_out": 0, "rows": 0}
        )
        st["posts"] += 1
        st["bytes_out"] += int(bytes_out)
        st["rows"] += int(rows)

    @property
    def wire_stats(self) -> Dict[str, Dict[str, int]]:
        """Per-encoding wire accounting: POSTs, body bytes out, and rows
        for every scoring chunk that succeeded — bytes/row per encoding
        is what the bench's ``client_bulk`` leg records."""
        return {enc: dict(st) for enc, st in self._wire_stats.items()}

    @staticmethod
    def replicas_from_watchman(snapshot: Dict[str, Any]) -> List[str]:
        """Replica base URLs from a watchman ``GET /`` snapshot body
        (the ``replicas`` list watchman derives from its scrape
        targets) — the hedging target list, fetched from the component
        that already tracks which replicas exist. Accepts both forms:
        bare URL strings (pre-mesh watchman) and the stamped entry
        objects (``{"url": ..., "routing_version": ..., "status": ...}``)
        the routing plane serves now."""
        out: List[str] = []
        for entry in snapshot.get("replicas") or []:
            if isinstance(entry, dict):
                entry = entry.get("url")
            if isinstance(entry, str) and entry.rstrip("/"):
                out.append(entry.rstrip("/"))
        return out

    # ------------------------------------------------------------------ #
    # partition-aware fan-out (the mesh routing table)
    # ------------------------------------------------------------------ #

    def _install_routing(self, table: Dict[str, Any]) -> None:
        """Validate + index a routing table body (watchman ``GET
        /routing``): member -> owner index, index -> replica entry."""
        if not isinstance(table, dict) or not isinstance(
            table.get("members"), dict
        ):
            raise ValueError(
                "routing table must be a dict with a 'members' map "
                "(watchman GET /routing body)"
            )
        replicas = {
            int(r["replica"]): {**r, "url": str(r["url"]).rstrip("/")}
            for r in table.get("replicas") or []
            if isinstance(r, dict) and "replica" in r and r.get("url")
        }
        self._routing = {
            "version": int(table.get("version", 0)),
            "members": dict(table["members"]),
            # member -> ALL replica indices serving it right now (the
            # table's multi-owner view: mid-migration overlap, or a
            # fully replicated fleet) — the hedge candidate set
            "owners": {
                str(k): [int(i) for i in v]
                for k, v in (table.get("migrating") or {}).items()
                if isinstance(v, (list, tuple))
            },
            "replicas": replicas,
        }

    @property
    def routing_version(self) -> Optional[int]:
        return self._routing["version"] if self._routing else None

    async def _fetch_routing(
        self, session, force: bool = False, member: Optional[str] = None
    ) -> bool:
        """Fetch/refresh the routing table from watchman. ETag-
        conditional: an unchanged table costs a 304 and keeps the local
        index. Returns True when the local table CHANGED. Best-effort —
        a watchman outage downgrades the run to single-URL posting (the
        configured base_url) rather than failing it.

        ``member`` (stale-table callers only) engages the per-member
        forced-refresh rate limit: at most one forced refetch per member
        per ``routing_refresh_window_s``; throttled calls return False
        without touching the network and count
        ``gordo_client_routing_refreshes_throttled_total``."""
        if self.routing_url is None:
            return False
        if force and member is not None:
            now = time.monotonic()
            last = self._forced_refresh_at.get(member)
            if (
                last is not None
                and now - last < self.routing_refresh_window_s
            ):
                self._fanout_stats["refreshes_throttled"] += 1
                return False
            # stamped BEFORE the attempt: a watchman that is down (the
            # storm case) must not be hammered by failed-refresh retries
            self._forced_refresh_at[member] = now
        headers = {}
        if self._routing_etag and not force:
            headers["If-None-Match"] = self._routing_etag
        try:
            async with session.get(
                f"{self.routing_url}/routing",
                params={"refresh": "1"} if force else None,
                headers=headers,
            ) as resp:
                if resp.status == 304:
                    return False
                if resp.status != 200:
                    logger.warning(
                        "routing fetch answered %d; keeping %s",
                        resp.status,
                        "previous table" if self._routing else "single-URL mode",
                    )
                    return False
                body = await resp.json()
                etag = resp.headers.get("ETag")
        except Exception as exc:
            logger.warning(
                "routing fetch from %s failed (%s); %s", self.routing_url,
                exc,
                "keeping previous table" if self._routing
                else "single-URL mode",
            )
            return False
        before = self._routing["version"] if self._routing else None
        try:
            # best-effort by contract: a 200 with an unexpected shape (a
            # proxy's JSON error page, a pre-mesh watchman) must downgrade
            # like any other fetch failure, not abort the scoring run —
            # and must NOT record the ETag, or conditional 304s would pin
            # the client table-less forever while it believes it is polling
            self._install_routing(body)
        except ValueError as exc:
            logger.warning(
                "routing body from %s unusable (%s); %s", self.routing_url,
                exc,
                "keeping previous table" if self._routing
                else "single-URL mode",
            )
            return False
        self._routing_etag = etag
        self._fanout_stats["routing_refreshes"] += 1
        return self._routing["version"] != before

    def _member_base_url(self, target: str) -> Optional[str]:
        """The owning replica's base URL for a member, or None (member
        unknown to the table, owner entry missing, or no table) — the
        caller falls back to the configured base_url, whose server
        answers 404 with the reason if truly nobody serves it."""
        if self._routing is None:
            return None
        idx = self._routing["members"].get(target)
        if idx is None:
            return None
        rep = self._routing["replicas"].get(int(idx))
        return rep["url"] if rep else None

    def _replica_healthy_for(self, rep: Dict[str, Any], target: str) -> bool:
        """Hedge/fallback eligibility from the routing table's stamps: a
        replica marked unreachable, degraded, or unhealthy — or one that
        QUARANTINES this member — must never receive a hedge (the old
        behavior hedged to any other replica, so a hedge could land on
        exactly the sick replica it was escaping)."""
        if not rep.get("reachable", True):
            return False
        if rep.get("status", "ok") not in ("ok",):
            return False
        return target not in (rep.get("quarantined") or ())

    def _connector_limit(self) -> int:
        """Keep-alive pool size for the scoring session. Hedged chunks
        open a SECOND in-flight socket while the primary is still
        running (client/io.py:fetch_json_hedged) — sizing the pool to
        ``parallelism`` alone made hedges queue behind the very sockets
        they were meant to bypass, so the slowest ~5% of chunks paid the
        hedge delay and then waited anyway. ``parallelism * (1 + hedge)``
        lanes plus a little control-plane headroom."""
        lanes = self.parallelism * (2 if self.hedge else 1)
        return max(lanes + 4, 8)

    def _hedge_delay_s(self) -> float:
        """Hedge after the observed p95 (only the slowest ~5% of chunks
        duplicate work); until enough samples exist, the configured
        initial delay applies."""
        if len(self._latency) >= _HEDGE_MIN_SAMPLES:
            p95 = self._latency.p95()
            if p95 is not None:
                return max(p95, 1e-3)
        return self.hedge_delay_init_s

    def _chunk_urls(self, target: str, endpoint: str) -> List[str]:
        """Primary URL plus (hedging only) ONE alternate replica's URL
        for the same path.

        With a routing table the primary is the member's OWNING replica
        (partition-aware fan-out: each chunk goes where the model's
        weights are resident), and the hedge alternate is drawn only
        from replicas the table marks healthy that also serve the member
        — in a partitioned fleet that usually means a mid-migration
        dual owner; a replica that doesn't hold the member, is
        degraded/unreachable, or quarantines it can only lose (or
        mis-404) the hedge."""
        if self._data_session is not None:
            # UDS session: the path is the address (the connector owns
            # the socket); hedging is TCP-replica machinery and a local
            # socket has no replicas — one URL, no hedge
            return [
                f"http://localhost/gordo/v0/{self.project}/{target}/{endpoint}"
            ]
        path = f"gordo/v0/{self.project}/{target}/{endpoint}"
        if self._routing is not None:
            primary = self._member_base_url(target) or self.base_url
            urls = [f"{primary}/{path}"]
            if self.hedge:
                # healthy replicas that actually SERVE this member (the
                # table's multi-owner set: mid-migration overlap, or a
                # replicated fleet) — never the sick-replica or
                # wrong-partition hedge the pre-routing client could
                # issue
                candidates = [
                    rep["url"]
                    for idx in self._routing["owners"].get(target, ())
                    if (rep := self._routing["replicas"].get(idx)) is not None
                    and rep["url"] != primary
                    and self._replica_healthy_for(rep, target)
                ]
                if candidates:
                    urls.append(f"{self._hedge_rng.choice(candidates)}/{path}")
            return urls
        urls = [self._url(target, endpoint)]
        if self.hedge:
            others = [u for u in self.replica_urls if u != self.base_url]
            if others:
                alt = self._hedge_rng.choice(others)
                urls.append(f"{alt}/{path}")
        return urls

    def _trace_headers(self, rid: str) -> Dict[str, str]:
        """Scoring-POST id headers: the gordo request id plus a W3C
        ``traceparent`` whose trace id is DERIVED from the request id
        (md5 — identity, not security), so a client log line and the
        server-side trace are the same identifier family and either one
        recovers the other. The sampled flag is set: a request the
        client bothered to stamp is one the operator wants retrievable
        at ``GET .../traces`` regardless of server head sampling.

        The QoS identity rides here too (when configured): the server's
        middleware classifies every scoring request from these headers,
        so one header pair covers the JSON, parquet, and tensor-over-
        HTTP encodings alike."""
        trace_id = hashlib.md5(rid.encode()).hexdigest()
        headers = {
            "X-Gordo-Request-Id": rid,
            "traceparent": format_traceparent(trace_id, trace_id[:16]),
        }
        if self.tenant:
            headers["X-Gordo-Tenant"] = self.tenant
        if self.qos_class != "interactive":
            headers["X-Gordo-Priority"] = self.qos_class
        return headers

    # ------------------------------------------------------------------ #
    # local zero-copy transports (docs/architecture.md "Serving
    # saturation"): negotiation + the shm scoring path
    # ------------------------------------------------------------------ #

    async def _resolve_transport(self, models_body) -> None:
        """Pick the scoring transport for this run. The ladder (shm >
        uds > tcp) combines local hints (``shm_ring=``/``uds_path=``)
        with the server's ``/models`` ``transports`` advertisement, and
        each rung must prove itself locally — attachable segment,
        present + connectable socket path — before it carries chunks.
        Every failure degrades one rung and logs why; tcp always
        works."""
        import os

        self.transport_used = "tcp"
        self._shm_client = None
        self._data_session = None
        if self.transport == "tcp":
            return
        adv = (models_body or {}).get("transports") or {}
        if self.transport in ("auto", "shm"):
            name = self.shm_ring or adv.get("shm")
            if name:
                try:
                    from gordo_components_tpu.utils.shm_ring import (
                        ShmRingClient,
                    )

                    self._shm_client = ShmRingClient(name)
                    self.transport_used = "shm"
                    logger.info("scoring over shm ring %r", name)
                    return
                except Exception as exc:
                    logger.warning(
                        "shm ring %r not attachable (%s); trying the next "
                        "transport", name, exc,
                    )
            elif self.transport == "shm":
                logger.warning(
                    "transport='shm' but no ring name (pass shm_ring= or "
                    "serve with GORDO_SHM_RING); falling back to tcp"
                )
        if self.transport in ("auto", "uds"):
            path = self.uds_path or adv.get("uds")
            if path and os.path.exists(path):
                try:
                    self._data_session = aiohttp.ClientSession(
                        timeout=aiohttp.ClientTimeout(total=600),
                        connector=aiohttp.UnixConnector(
                            path=path, limit=self._connector_limit()
                        ),
                    )
                    self.transport_used = "uds"
                    logger.info("scoring over unix socket %s", path)
                    return
                except Exception as exc:
                    logger.warning(
                        "unix socket %s not usable (%s); falling back to "
                        "tcp", path, exc,
                    )
            elif self.transport == "uds":
                logger.warning(
                    "transport='uds' but socket path %r does not exist; "
                    "falling back to tcp", path,
                )

    async def _drop_uds(self, exc) -> None:
        """Retire a dead unix-socket session mid-run (idempotent under
        concurrent chunks: first caller wins, the rest see tcp). The
        session object is parked for end-of-run closing — see
        ``_dead_sessions``."""
        s, self._data_session = self._data_session, None
        self.transport_used = "tcp"
        if s is not None:
            logger.warning(
                "unix-socket transport failed mid-run (%s); remaining "
                "chunks go over tcp", exc,
            )
            self._dead_sessions.append(s)

    async def _post_shm(
        self, target: str, endpoint: str, chunk: pd.DataFrame,
        chunk_y: Optional[pd.DataFrame],
        deadline: Optional[Deadline] = None,
    ) -> pd.DataFrame:
        """One chunk over the shared-memory ring: same tensor body, same
        response bytes, no socket. The ring wait runs on an executor
        thread — the event loop keeps pumping the other chunks.

        Same transient-failure citizenship as the HTTP path
        (client/io.py): 408/429/5xx retry on decorrelated jitter
        (honoring a 429 body's ``retry_after_s`` drain estimate as a
        lower bound) through the shared retry budget; other non-200s
        raise ``ValueError`` with the server's error document. The
        chunk's ``deadline`` bounds the whole exchange CLIENT-side (ring
        wait capped at the remaining budget, no retry sleep past
        expiry, ``DeadlineExceeded`` once spent) — the slot envelope
        carries no deadline field, so server-side expiry dropping is
        the one HTTP nicety the shm rung does not replicate."""
        from gordo_components_tpu.resilience.retry_budget import (
            decorrelated_jitter,
        )

        body = await asyncio.get_running_loop().run_in_executor(
            None, self._encode_tensor, chunk, chunk_y
        )
        kind = "anomaly" if endpoint.startswith("anomaly") else "prediction"
        self.retry_budget.note_request()
        retries = max(1, self.retries)
        prev_delay = self.backoff
        for attempt in range(retries):
            if deadline is not None and deadline.expired():
                raise DeadlineExceeded(
                    f"deadline expired before shm attempt {attempt + 1}"
                )
            ring_timeout = 60.0
            if deadline is not None:
                ring_timeout = max(1e-3, min(ring_timeout, deadline.remaining_s()))
            status, resp = await asyncio.get_running_loop().run_in_executor(
                None,
                functools.partial(
                    self._shm_client.request, target, body, kind,
                    timeout=ring_timeout,
                ),
            )
            if status < 400:
                self._note_wire("tensor", len(body), len(chunk))
                return self._decode_tensor_scoring_body(
                    resp, chunk, anomaly=kind == "anomaly"
                )
            if status not in (408, 429) and status < 500:
                break  # genuine request error: retrying cannot help
            if attempt + 1 >= retries or not self.retry_budget.try_spend():
                break
            delay = prev_delay = decorrelated_jitter(
                self.backoff, prev_delay
            )
            if status == 429:
                try:  # the shed response's queue-drain estimate
                    hinted = float(json.loads(resp).get("retry_after_s", 0))
                    delay = max(delay, min(hinted, 60.0))
                except (ValueError, AttributeError):
                    pass
            if deadline is not None:
                # never sleep past our own expiry (same rule as io.py)
                delay = min(delay, deadline.remaining_s())
            await asyncio.sleep(delay)
        raise ValueError(
            f"shm status {status}: {resp[:500].decode('utf-8', 'replace')}"
        )

    # ------------------------------------------------------------------ #

    def _url(self, target: str, endpoint: str) -> str:
        # control-plane lookups follow the routing table too: in a
        # partitioned mesh only the OWNER can answer a member's
        # /metadata (the configured base_url would 404 the other
        # partitions' members)
        base = self._member_base_url(target) or self.base_url
        return f"{base}/gordo/v0/{self.project}/{target}/{endpoint}"

    async def _get_metadata(self, session, target: str) -> Dict[str, Any]:
        meta = self._metadata_all.get(target)
        if meta is not None:
            return meta

        async def fetch():
            body = await fetch_json(
                session,
                self._url(target, "metadata"),
                retries=self.retries,
                backoff=self.backoff,
                retry_budget=self.retry_budget,
            )
            return body.get("endpoint-metadata", {})

        try:
            return await fetch()
        except ValueError as exc:
            # routed 404: the member may have MOVED since our table
            # (stale-table detection, same rule as the scoring path) —
            # one forced refetch, one retry against the new owner
            if self._routing is None or "404" not in str(exc):
                raise
            if not await self._fetch_routing(session, force=True, member=target):
                raise
            logger.warning(
                "routing table was stale (now v%s); refetching metadata "
                "for %s", self.routing_version, target,
            )
            self._fanout_stats["reroutes"] += 1
            return await fetch()

    async def _prefetch_metadata(self, session) -> None:
        """Prefetch every target's metadata in ONE request via the
        collection server's batched control-plane endpoint — at fleet
        scale the per-target ``/metadata`` round-trips otherwise cost N
        requests before any scoring starts. Best-effort with a short
        deadline and shape validation (shared helper, client/io.py):
        foreign servers keep the per-target path.

        Partitioned mesh: ONE metadata-all per replica (each holds only
        its partition's metadata), merged — still O(replicas), not
        O(members), requests."""
        bases = [self.base_url]
        if self._routing is not None:
            routed = [
                rep["url"]
                for rep in self._routing["replicas"].values()
                if rep.get("reachable", True)
            ]
            bases = routed or bases
        bodies = await asyncio.gather(
            *(fetch_metadata_all(session, b, self.project) for b in bases)
        )
        merged: Dict[str, Any] = {}
        for body in bodies:
            if body is None:
                continue
            merged.update(
                {
                    name: entry["endpoint-metadata"]
                    for name, entry in body["targets"].items()
                    if isinstance(entry, dict) and "endpoint-metadata" in entry
                }
            )
        self._metadata_all = merged

    def _dataset_config_from_metadata(self, meta, start, end) -> Dict[str, Any]:
        ds_meta = meta.get("dataset", {})
        config = self.metadata_fallback_dataset or {"type": "RandomDataset"}
        if ds_meta:
            # Tag dicts ({name, asset}) pass through whole: dropping asset
            # would break providers with asset-scoped layouts; row_filter and
            # aggregation must match training or scored rows diverge from
            # what the model saw.
            config = {
                "type": ds_meta.get("type", "TimeSeriesDataset"),
                "tag_list": ds_meta.get("tag_list", []),
                "resolution": ds_meta.get("resolution", "10min"),
                "aggregation_method": ds_meta.get("aggregation_method", "mean"),
                "row_filter": ds_meta.get("row_filter", ""),
                "data_provider": ds_meta.get("data_provider"),
            }
            if ds_meta.get("target_tag_list"):
                config["target_tag_list"] = ds_meta["target_tag_list"]
            if not isinstance(config["data_provider"], dict):
                # only a provider dict can be re-instantiated by the
                # dataset layer; a repr string cannot
                config.pop("data_provider", None)
        return {
            **config,
            "train_start_date": str(start),
            "train_end_date": str(end),
        }

    # ------------------------------------------------------------------ #

    def predict(
        self, start: pd.Timestamp, end: pd.Timestamp, targets: Optional[List[str]] = None
    ) -> List[PredictionResult]:
        """Synchronous entrypoint (reference CLI semantics)."""
        return asyncio.run(self.predict_async(start, end, targets))

    async def predict_async(
        self, start, end, targets: Optional[List[str]] = None
    ) -> List[PredictionResult]:
        timeout = aiohttp.ClientTimeout(total=600)
        sem = asyncio.Semaphore(self.parallelism)
        # keep-alive connections bounded a little above the chunk
        # concurrency: every chunk POST reuses a warm socket instead of
        # paying handshake latency per request. Sized for HEDGES too
        # (_connector_limit): a hedged chunk holds two sockets at once,
        # and a pool pinned to bare parallelism made hedges queue behind
        # the primaries they were escaping.
        connector = aiohttp.TCPConnector(limit=self._connector_limit())
        async with aiohttp.ClientSession(
            timeout=timeout, connector=connector
        ) as session:
            # partition-aware fan-out: learn the routing table BEFORE
            # discovery — in a mesh the configured base_url is one
            # replica and its /models lists only its own partition, so
            # the table (union over the fleet) is the real target roster
            if self.routing_url is not None:
                await self._fetch_routing(session)
            models_body = None
            if (
                targets is None
                or self.use_parquet == "auto"
                or self.use_tensor == "auto"
                or self.transport in ("auto", "uds", "shm")
            ):
                try:
                    models_body = await fetch_json(
                        session,
                        f"{self.base_url}/gordo/v0/{self.project}/models",
                        retries=self.retries,
                        backoff=self.backoff,
                        retry_budget=self.retry_budget,
                    )
                except Exception:
                    if targets is None and not (
                        self._routing and self._routing["members"]
                    ):
                        raise  # discovery is mandatory without a table
                    models_body = None  # encoding probe is best-effort
            if targets is None:
                if self._routing is not None and self._routing["members"]:
                    targets = sorted(self._routing["members"])
                else:
                    # a VALID-but-empty table (fleet still booting,
                    # replicas momentarily unreachable) must not quietly
                    # score nothing: the base replica's /models is live
                    # discovery truth we already fetched
                    targets = models_body["models"]
            # fresh per run: stale cached metadata must never outlive a
            # server-side /reload (a failed re-prefetch then falls back to
            # per-target fetches, not to last run's cache)
            self._metadata_all = {}
            if len(targets) >= _PREFETCH_MIN_TARGETS:
                # below that, per-target GETs are cheaper than pulling the
                # whole fleet's metadata for a handful of lookups
                await self._prefetch_metadata(session)
            if self.use_tensor == "auto":
                # tensor-first negotiation: exact content-type match (a
                # substring test would let a foreign "x-gordo-tensor-v9"
                # advertisement negotiate a format we don't speak)
                self._tensor_active = any(
                    a == TENSOR_CONTENT_TYPE
                    for a in (models_body or {}).get("accepts", [])
                )
            else:
                self._tensor_active = bool(self.use_tensor)
            if self.use_parquet == "auto":
                self._parquet_active = parquet_engine_available() and any(
                    "parquet" in a
                    for a in (models_body or {}).get("accepts", [])
                )
            else:
                self._parquet_active = bool(self.use_parquet)
                if self._parquet_active and not parquet_engine_available():
                    # forced mode fails loudly up front, not one opaque
                    # to_parquet ImportError per chunk
                    raise ImportError(
                        "use_parquet=True but no parquet engine "
                        "(pyarrow/fastparquet) is installed"
                    )
            if (
                self._routing is not None
                and len(self._routing["replicas"]) > 1
            ):
                # fan-out across replicas rides TCP: the uds/shm rungs
                # address ONE co-located server, and pinning every
                # routed chunk to a local socket would undo the
                # partition routing the table exists for
                self.transport_used = "tcp"
                self._shm_client = None
                self._data_session = None
            else:
                await self._resolve_transport(models_body)
            try:
                results = await asyncio.gather(
                    *(
                        self._predict_single(session, sem, t, start, end)
                        for t in targets
                    )
                )
            finally:
                if self._data_session is not None:
                    await self._data_session.close()
                    self._data_session = None
                for dead in self._dead_sessions:
                    with contextlib.suppress(Exception):
                        await dead.close()
                self._dead_sessions = []
                if self._shm_client is not None:
                    self._shm_client.close()
                    self._shm_client = None
        if self.forwarder is not None:
            for result in results:
                if result.ok:
                    self.forwarder.forward(result)
        return list(results)

    @staticmethod
    def _encode_parquet(chunk: pd.DataFrame, chunk_y) -> bytes:
        """Serialize one chunk as parquet bytes (runs on an executor
        thread: CPU-bound encoding must not stall the event loop that is
        pumping the in-flight POSTs — the overlap half of the data-plane
        win). Engine pinned once (utils/encoding.py) so pandas' per-call
        "auto" resolution never rides the chunk loop."""
        import io

        frame = chunk
        if chunk_y is not None:
            # indices are identical by construction (iloc slices of the
            # same row range), so this is a pure column concat
            frame = pd.concat([chunk, chunk_y.add_prefix("__y__")], axis=1)
        buf = io.BytesIO()
        frame.to_parquet(buf, engine=parquet_engine() or "auto")
        return buf.getvalue()

    async def _post_parquet(
        self, session, target, endpoint, chunk: pd.DataFrame,
        chunk_y: Optional[pd.DataFrame] = None,
        request_id: Optional[str] = None,
        deadline: Optional[Deadline] = None,
    ):
        """POST one chunk as a parquet body (index rides inside the file,
        so timestamps round-trip without the JSON string lists). Target
        columns for supervised machines are embedded under a ``__y__``
        prefix; the server splits them back out (server/utils.py)."""
        body = await asyncio.get_running_loop().run_in_executor(
            None, self._encode_parquet, chunk, chunk_y
        )
        headers = {"Content-Type": "application/x-parquet"}
        if request_id:
            headers.update(self._trace_headers(request_id))
        resp = await fetch_json_hedged(
            session,
            self._chunk_urls(target, endpoint),
            hedge_delay_s=self._hedge_delay_s(),
            hedge_stats=self._hedge_stats,
            method="POST",
            data=body,
            headers=headers,
            retries=self.retries,
            backoff=self.backoff,
            retry_budget=self.retry_budget,
            deadline=deadline,
        )
        self._note_wire("parquet", len(body), len(chunk))
        return resp

    def _encode_tensor(self, chunk: pd.DataFrame, chunk_y) -> bytes:
        """One chunk as a framed tensor body (utils/wire.py): the float32
        rows in C order, one memory copy total. Runs on an executor
        thread so chunk k+1 serializes while chunk k's POST is in flight
        (with tensor framing the encode is ~µs — the executor hop is for
        symmetry with the other encoders and for very large chunks).

        When a QoS identity is configured it rides in a ``__meta__``
        sidecar frame (JSON bytes): the shm ring has no headers and
        proxies may strip custom ones, so the framed body itself must
        carry tenant + priority for fairness to hold on every
        transport."""
        frames = [("X", np.ascontiguousarray(chunk.values, dtype=np.float32))]
        if chunk_y is not None:
            frames.append(
                ("y", np.ascontiguousarray(chunk_y.values, dtype=np.float32))
            )
        meta: Dict[str, str] = {}
        if self.tenant:
            meta["tenant"] = self.tenant
        if self.qos_class != "interactive":
            meta["priority"] = self.qos_class
        if meta:
            frames.append((
                "__meta__",
                np.frombuffer(
                    json.dumps(meta).encode("utf-8"), dtype=np.uint8
                ),
            ))
        return pack_frames(frames)

    def _decode_tensor_scoring_body(
        self, body: bytes, chunk: pd.DataFrame, anomaly: bool
    ) -> pd.DataFrame:
        """Tensor response -> the SAME DataFrame the JSON path builds
        (column-for-column, value-for-value: float32 -> float64 is exact,
        so frames from either encoding are bitwise interchangeable). The
        index is the client's own chunk index trimmed by the server's
        ``offset`` — no stringified-timestamp round trip."""
        frames = unpack_frames(body)
        meta = json.loads(bytes(frames.pop("__meta__")))
        offset = int(meta.get("offset", 0))
        if anomaly:
            tags = meta["tags"]
            cols: Dict[Any, np.ndarray] = {}
            for top in ANOMALY_FRAME_NAMES[:4]:
                arr = frames[top].astype(np.float64)
                for i, tag in enumerate(tags):
                    cols[(top, tag)] = arr[:, i]
            for top in ANOMALY_FRAME_NAMES[4:]:
                cols[(top, "")] = frames[top].astype(np.float64)
            df = pd.DataFrame(cols)
            df.columns = pd.MultiIndex.from_tuples(df.columns)
        else:
            df = pd.DataFrame(frames["data"].astype(np.float64))
        df.index = chunk.index[offset : offset + len(df)]
        return df

    async def _post_tensor(
        self, session, target, endpoint, chunk: pd.DataFrame,
        chunk_y: Optional[pd.DataFrame] = None,
        request_id: Optional[str] = None,
        deadline: Optional[Deadline] = None,
    ) -> pd.DataFrame:
        """POST one chunk as a framed tensor body and decode the binary
        response straight into the result frame."""
        body = await asyncio.get_running_loop().run_in_executor(
            None, self._encode_tensor, chunk, chunk_y
        )
        headers = {"Content-Type": TENSOR_CONTENT_TYPE}
        if request_id:
            headers.update(self._trace_headers(request_id))
        resp = await fetch_json_hedged(
            session,
            self._chunk_urls(target, endpoint),
            hedge_delay_s=self._hedge_delay_s(),
            hedge_stats=self._hedge_stats,
            method="POST",
            data=body,
            headers=headers,
            retries=self.retries,
            backoff=self.backoff,
            retry_budget=self.retry_budget,
            deadline=deadline,
        )
        if not isinstance(resp, (bytes, bytearray)):
            # a 200 with a JSON body to a tensor POST is a foreign server
            # that ignored the content type; surface it like a rejection
            # so auto mode downgrades instead of mis-parsing
            raise ValueError(
                f"server answered a tensor POST with {type(resp).__name__}, "
                "not a tensor body"
            )
        self._note_wire("tensor", len(body), len(chunk))
        return self._decode_tensor_scoring_body(
            resp, chunk, anomaly=endpoint.startswith("anomaly")
        )

    async def _predict_single(
        self, session, sem, target: str, start, end
    ) -> PredictionResult:
        try:
            meta = await self._get_metadata(session, target)
            config = self._dataset_config_from_metadata(meta, start, end)
            dataset = get_dataset(config)
        except Exception as exc:
            logger.exception("Failed to resolve dataset config for %s", target)
            return PredictionResult(target, None, [f"dataset: {exc}"])
        try:
            fetch = asyncio.get_running_loop().run_in_executor(
                None, dataset.get_data
            )
            if self.deadline_ms is not None:
                # a hung data provider must not stall a backfill slot
                # forever: the dataset build gets the same budget as a
                # chunk POST (the executor job itself can't be
                # interrupted, but the slot moves on and reports).
                # Deliberately its OWN try block: a metadata-fetch
                # timeout above must not land in this handler
                fetch = asyncio.wait_for(fetch, timeout=self.deadline_ms / 1e3)
                try:
                    X, y = await fetch
                except asyncio.TimeoutError:
                    logger.error(
                        "Dataset build for %s exceeded the %.0fms deadline",
                        target, self.deadline_ms,
                    )
                    return PredictionResult(
                        target, None,
                        [
                            f"dataset: build exceeded "
                            f"{self.deadline_ms:.0f}ms deadline"
                        ],
                    )
            else:
                X, y = await fetch
        except Exception as exc:
            logger.exception("Failed to build dataset for %s", target)
            return PredictionResult(target, None, [f"dataset: {exc}"])

        endpoint = "anomaly/prediction" if self.use_anomaly else "prediction"
        frames: List[pd.DataFrame] = []
        errors: List[str] = []

        async def post_chunk(chunk: pd.DataFrame, chunk_y: Optional[pd.DataFrame]):
            async with sem:
                # routed-chunk accounting lives HERE, once per chunk
                # attempt — _chunk_urls runs once per encoding rung
                # (tensor -> parquet -> JSON downgrades), which would
                # count one chunk several times and skew the
                # routed-vs-fallback split the replica-loss runbook
                # reads. A no-owner fallback to base_url never counts.
                if (
                    self._routing is not None
                    and self._member_base_url(target) is not None
                ):
                    self._fanout_stats["routed_chunks"] += 1
                # one id per chunk, reused across the tensor/parquet ->
                # JSON downgrade re-posts: every attempt is the SAME
                # request. Likewise ONE deadline: a downgrade re-post
                # spends what remains of the chunk's budget, not a fresh
                # one.
                rid = self._next_request_id()
                deadline = (
                    Deadline.after_ms(self.deadline_ms)
                    if self.deadline_ms is not None
                    else None
                )
                t0 = asyncio.get_running_loop().time()
                tensor_exc = parquet_exc = None
                # captured ONCE per chunk: when the unix socket dies
                # mid-run, every in-flight sibling fails with the same
                # ClientConnectionError, and each must know it was on
                # the (now-retired) uds session — reading
                # self._data_session after the first sibling nulled it
                # would make the rest give up instead of retrying tcp
                data_sess = self._data_session
                if self._shm_client is not None and self._tensor_active:
                    # the shared-memory rung: same tensor body, same
                    # response bytes, zero sockets. A ring-level failure
                    # degrades the RUN to the HTTP rungs below; a 4xx is
                    # a genuine request error (the ring only ever faces
                    # a gordo server, so there is no foreign-server
                    # ambiguity to disambiguate).
                    try:
                        frame = await self._post_shm(
                            target, endpoint, chunk, chunk_y,
                            deadline=deadline,
                        )
                        self._latency.record(
                            asyncio.get_running_loop().time() - t0
                        )
                        return frame
                    except DeadlineExceeded as exc:
                        errors.append(
                            f"chunk {chunk.index[0]} (rid={rid}): deadline: {exc}"
                        )
                        return None
                    except ValueError as exc:
                        errors.append(f"chunk {chunk.index[0]} (rid={rid}): {exc}")
                        return None
                    except Exception as exc:
                        logger.warning(
                            "shm transport failed (%s); falling back to "
                            "HTTP for the rest of the run", exc,
                        )
                        shm, self._shm_client = self._shm_client, None
                        self.transport_used = (
                            "uds" if self._data_session is not None else "tcp"
                        )
                        with contextlib.suppress(Exception):
                            shm.close()
                if self._tensor_active:
                    try:
                        frame = await self._post_tensor(
                            data_sess or session, target, endpoint,
                            chunk, chunk_y, request_id=rid, deadline=deadline,
                        )
                        self._latency.record(
                            asyncio.get_running_loop().time() - t0
                        )
                        return frame
                    except aiohttp.ClientConnectionError as exc:
                        if data_sess is None:
                            errors.append(
                                f"chunk {chunk.index[0]} (rid={rid}): {exc}"
                            )
                            return None
                        # mid-run unix-socket death (server restarted
                        # without its UDS listener, path unlinked):
                        # degrade the run to tcp and retry THIS chunk —
                        # a transport failure must not masquerade as an
                        # encoding rejection and cost the run its
                        # tensor upgrade
                        await self._drop_uds(exc)
                        try:
                            frame = await self._post_tensor(
                                session, target, endpoint, chunk, chunk_y,
                                request_id=rid, deadline=deadline,
                            )
                            self._latency.record(
                                asyncio.get_running_loop().time() - t0
                            )
                            return frame
                        except Exception as exc2:
                            errors.append(
                                f"chunk {chunk.index[0]} (rid={rid}): {exc2}"
                            )
                            return None
                    except ValueError as exc:
                        # 4xx on the tensor body: foreign server (or a
                        # genuine model error that any encoding would
                        # 400). The fallback posts below disambiguate —
                        # forced mode never downgrades, same contract as
                        # parquet.
                        if self.use_tensor is True:
                            errors.append(
                                f"chunk {chunk.index[0]} (rid={rid}): {exc}"
                            )
                            return None
                        tensor_exc = exc
                    except Exception as exc:
                        errors.append(f"chunk {chunk.index[0]} (rid={rid}): {exc}")
                        return None
                if self._parquet_active:
                    try:
                        body = await self._post_parquet(
                            data_sess or session, target, endpoint,
                            chunk, chunk_y, request_id=rid, deadline=deadline,
                        )
                        self._latency.record(
                            asyncio.get_running_loop().time() - t0
                        )
                        if tensor_exc is not None:
                            # parquet succeeded where tensor 4xx'd: an
                            # encoding problem — downgrade the run
                            logger.warning(
                                "tensor body rejected (%s) but parquet "
                                "succeeded; downgrading run", tensor_exc,
                            )
                            self._tensor_active = False
                        return body
                    except aiohttp.ClientConnectionError as exc:
                        if data_sess is None:
                            errors.append(
                                f"chunk {chunk.index[0]} (rid={rid}): {exc}"
                            )
                            return None
                        # unix socket died mid-run: degrade to tcp and
                        # fall through to the JSON rung below — a
                        # transport failure is not an encoding verdict
                        await self._drop_uds(exc)
                        data_sess = None
                    except ValueError as exc:
                        # 4xx on the parquet body. Ambiguous: the server
                        # may reject the ENCODING (foreign pod, no parse
                        # engine) or this chunk may hit a genuine model
                        # error that would 400 under any encoding. The
                        # JSON re-post below disambiguates; forced mode
                        # never downgrades (documented contract).
                        if self.use_parquet is True:
                            errors.append(f"chunk {chunk.index[0]} (rid={rid}): {exc}")
                            return None
                        parquet_exc = exc
                    except Exception as exc:
                        errors.append(f"chunk {chunk.index[0]} (rid={rid}): {exc}")
                        return None
                payload = {
                    "X": chunk.values.tolist(),
                    "index": [str(i) for i in chunk.index],
                }
                if chunk_y is not None:
                    payload["y"] = chunk_y.values.tolist()
                # encode off the event loop (same overlap contract as the
                # binary encoders: a 500-row float-list dumps() is
                # milliseconds the in-flight POSTs shouldn't stall on),
                # and as bytes so the wire accounting sees real sizes
                json_body = await asyncio.get_running_loop().run_in_executor(
                    None,
                    functools.partial(json.dumps, payload, ensure_ascii=False),
                )
                json_body = json_body.encode("utf-8")

                async def _post_json(sess):
                    return await fetch_json_hedged(
                        sess,
                        self._chunk_urls(target, endpoint),
                        hedge_delay_s=self._hedge_delay_s(),
                        hedge_stats=self._hedge_stats,
                        method="POST",
                        data=json_body,
                        headers={
                            "Content-Type": "application/json",
                            **self._trace_headers(rid),
                        },
                        retries=self.retries,
                        backoff=self.backoff,
                        retry_budget=self.retry_budget,
                        deadline=deadline,
                    )

                try:
                    body = await _post_json(data_sess or session)
                    self._latency.record(asyncio.get_running_loop().time() - t0)
                    self._note_wire("json", len(json_body), len(chunk))
                except aiohttp.ClientConnectionError as exc:
                    if data_sess is None:
                        errors.append(
                            f"chunk {chunk.index[0]} (rid={rid}): {exc}"
                        )
                        return None
                    # same mid-run unix-socket death handling as the
                    # tensor rung: degrade to tcp and retry this chunk
                    await self._drop_uds(exc)
                    try:
                        body = await _post_json(session)
                        self._latency.record(
                            asyncio.get_running_loop().time() - t0
                        )
                        self._note_wire("json", len(json_body), len(chunk))
                    except Exception as exc2:
                        errors.append(
                            f"chunk {chunk.index[0]} (rid={rid}): {exc2}"
                        )
                        return None
                except DeadlineExceeded as exc:
                    errors.append(
                        f"chunk {chunk.index[0]} (rid={rid}): deadline: {exc}"
                    )
                    return None
                except Exception as exc:
                    errors.append(f"chunk {chunk.index[0]} (rid={rid}): {exc}")
                    return None
                if tensor_exc is not None:
                    logger.warning(
                        "tensor body rejected (%s) but JSON succeeded; "
                        "downgrading run", tensor_exc,
                    )
                    self._tensor_active = False
                if parquet_exc is not None:
                    # JSON succeeded where parquet 4xx'd: an encoding
                    # problem, not a model error — downgrade the rest of
                    # the run (a model error would have failed both and
                    # must NOT cost the whole fleet its parquet win)
                    logger.warning(
                        "parquet body rejected (%s) but JSON succeeded; "
                        "downgrading run to JSON", parquet_exc,
                    )
                    self._parquet_active = False
                return body

        # y rides along for supervised machines (target_tag_list): the
        # anomaly diff must be computed against the TRAINED target, not
        # X->X — silently dropping y here would score the wrong objective
        chunks = [
            (
                X.iloc[i : i + self.batch_size],
                None if y is None else y.iloc[i : i + self.batch_size],
            )
            for i in range(0, len(X), self.batch_size)
        ]
        bodies = await asyncio.gather(*(post_chunk(cx, cy) for cx, cy in chunks))
        if (
            self._routing is not None
            and any(b is None for b in bodies)
            and any("No such model" in e for e in errors)
        ):
            # stale-table detection: a routed chunk 404ing means the
            # member moved since our table (watchman stamps the version
            # for exactly this). Refetch once; a CHANGED table re-posts
            # every failed chunk to the new owner — one bounded retry,
            # not a loop (an unchanged table means the member truly has
            # no owner, and the 404-with-reason stands as the answer)
            if await self._fetch_routing(session, force=True, member=target):
                retry = [i for i, b in enumerate(bodies) if b is None]
                self._fanout_stats["reroutes"] += len(retry)
                logger.warning(
                    "routing table was stale (now v%s); re-posting %d "
                    "chunk(s) for %s", self.routing_version, len(retry),
                    target,
                )
                errors.clear()
                fresh = await asyncio.gather(
                    *(post_chunk(*chunks[i]) for i in retry)
                )
                for i, body in zip(retry, fresh):
                    bodies[i] = body
        for body in bodies:
            if body is None:
                continue
            if isinstance(body, pd.DataFrame):
                # the tensor path decodes straight to the result frame
                frames.append(body)
            elif "data" in body and isinstance(body["data"], dict):
                frames.append(dict_to_frame(body))
            elif "data" in body:
                df = pd.DataFrame(body["data"])
                if body.get("index") and len(body["index"]) == len(df):
                    df.index = pd.to_datetime(body["index"], utc=True)
                frames.append(df)
        predictions = pd.concat(frames) if frames else None
        return PredictionResult(target, predictions, errors)

    # ------------------------------------------------------------------ #
    # streaming forwarder
    # ------------------------------------------------------------------ #

    def ingest(
        self, target: str, X, timestamps=None, tensor: bool = False
    ) -> Dict[str, int]:
        """Synchronous wrapper over :meth:`ingest_async`."""
        return asyncio.run(self.ingest_async(target, X, timestamps, tensor=tensor))

    async def ingest_async(
        self, target: str, X, timestamps=None, tensor: bool = False
    ) -> Dict[str, int]:
        """Streaming forwarder: POST fresh rows to the server's
        ``.../{target}/ingest`` window buffer in ``batch_size``-row
        chunks, reusing the scoring path's transport citizenship — the
        per-chunk deadline rides the wire as ``X-Gordo-Deadline-Ms``
        (restamped per retry attempt) and every retry spends the SAME
        shared :class:`RetryBudget` the scoring POSTs draw from, so an
        ingest storm cannot re-offer unbounded load either. NaN cells
        (sensor dropout) serialize as JSON ``null``.

        ``X``: DataFrame (index supplies event timestamps unless
        ``timestamps`` is given) or (rows, features) array.
        Returns the summed server accounting
        (``accepted``/``late``/``dropped`` rows + chunks posted) and
        feeds ``gordo_client_ingest_rows_total``.

        ``tensor=True`` posts each chunk as a framed tensor body (the
        scoring plane's wire format, utils/wire.py): float32 ``rows``
        (NaN cells ARE the dropout markers — no null boxing) plus a
        float64 epoch-seconds ``timestamps`` frame. Explicit opt-in
        because the ingest path does no ``/models`` negotiation — use it
        against gordo servers, not foreign ones.

        Delivery is AT-LEAST-ONCE: a chunk the server ingested whose
        response was lost gets retried and its rows ingested twice.
        That is the right trade for a drift window (a few duplicated
        rows barely move an EWMA/quantile; silently LOSING fresh rows
        starves detection) — but it means ``rows_total`` is an upper
        bound on distinct rows, not an exact count."""
        if isinstance(X, pd.DataFrame):
            values = X.values
            if timestamps is None and isinstance(X.index, pd.DatetimeIndex):
                # only a datetime index carries event times; a default
                # RangeIndex would serialize as unparseable "0","1",...
                # — omit instead, the server stamps arrival time
                timestamps = [str(i) for i in X.index]
        else:
            values = np.asarray(X)
        epoch_ts = None
        if tensor and timestamps is not None:
            # the wire frame wants epoch seconds; string/Timestamp forms
            # are normalized once up front (ns -> s, matching the server)
            ts_list = list(timestamps)
            if ts_list and isinstance(
                ts_list[0], (int, float, np.integer, np.floating)
            ):
                epoch_ts = np.asarray(ts_list, np.float64)
            else:  # ISO strings / Timestamps: one vectorized parse
                epoch_ts = (
                    pd.to_datetime(ts_list, utc=True).as_unit("ns").asi8 / 1e9
                )
        totals = {"accepted": 0, "late": 0, "dropped": 0, "chunks": 0}
        url = self._url(target, "ingest")
        timeout = aiohttp.ClientTimeout(total=600)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            for i in range(0, len(values), self.batch_size):
                chunk = values[i : i + self.batch_size]
                rid = self._next_request_id()
                deadline = (
                    Deadline.after_ms(self.deadline_ms)
                    if self.deadline_ms is not None
                    else None
                )
                if tensor:
                    frames = [
                        ("rows", np.ascontiguousarray(chunk, dtype=np.float32))
                    ]
                    if epoch_ts is not None:
                        frames.append(
                            ("timestamps", epoch_ts[i : i + self.batch_size])
                        )
                    data = pack_frames(frames)
                    body = await fetch_json(
                        session,
                        url,
                        method="POST",
                        data=data,
                        headers={
                            "Content-Type": TENSOR_CONTENT_TYPE,
                            **self._trace_headers(rid),
                        },
                        retries=self.retries,
                        backoff=self.backoff,
                        retry_budget=self.retry_budget,
                        deadline=deadline,
                    )
                    # its own bucket: mixing ingest traffic into the
                    # scoring "tensor" cell would skew the bytes-per-row
                    # comparison the bench legs read
                    self._note_wire("ingest-tensor", len(data), len(chunk))
                else:
                    rows = [
                        [None if v != v else float(v) for v in row]
                        for row in chunk.tolist()
                    ]
                    payload: Dict[str, Any] = {"rows": rows}
                    if timestamps is not None:
                        ts = list(timestamps[i : i + self.batch_size])
                        payload["timestamps"] = [
                            t if isinstance(t, (int, float, str)) else str(t)
                            for t in ts
                        ]
                    data = json.dumps(payload).encode("utf-8")
                    body = await fetch_json(
                        session,
                        url,
                        method="POST",
                        data=data,
                        headers={
                            "Content-Type": "application/json",
                            **self._trace_headers(rid),
                        },
                        retries=self.retries,
                        backoff=self.backoff,
                        retry_budget=self.retry_budget,
                        deadline=deadline,
                    )
                    # symmetric with the tensor branch: ingest bytes in
                    # their own bucket, never the scoring cells
                    self._note_wire("ingest-json", len(data), len(chunk))
                totals["chunks"] += 1
                for key in ("accepted", "late", "dropped"):
                    totals[key] += int(body.get(key, 0))
                self._ingest_stats["rows"] += int(body.get("accepted", 0))
                self._ingest_stats["chunks"] += 1
        return totals
