"""Async HTTP helpers for the bulk client.

Reference parity: gordo_components/client/io.py (unverified; SURVEY.md §2
"client") — bounded-concurrency POSTs with retry/backoff. Grown into the
client half of the overload defense (resilience/):

- retries sleep on DECORRELATED JITTER, not ``backoff * 2**attempt`` —
  deterministic exponential backoff synchronizes chunks that failed
  together, so every retry wave re-creates the overload it backed off
  from (the metastable-overload recipe);
- a shared :class:`~gordo_components_tpu.resilience.retry_budget.RetryBudget`
  token bucket gates every retry, capping a client's re-offered load at
  ``1 + ratio`` times its offered load by arithmetic;
- per-request :class:`~gordo_components_tpu.resilience.deadline.Deadline`
  budgets are stamped onto the wire (``X-Gordo-Deadline-Ms``) so the
  server can drop the request once the client stops waiting, and bound
  each attempt locally;
- :func:`fetch_json_hedged` trades a bounded amount of duplicate work
  for tail latency: after a (p95-derived) delay, re-issue the request to
  a second replica and take the first success.
"""

import asyncio
import logging
import random
from typing import Any, Dict, List, Optional

import aiohttp

from gordo_components_tpu.resilience.deadline import (
    DEADLINE_HEADER,
    Deadline,
    DeadlineExceeded,
)
from gordo_components_tpu.resilience.retry_budget import (
    RetryBudget,
    decorrelated_jitter,
)
from gordo_components_tpu.utils.wire import TENSOR_CONTENT_TYPE

logger = logging.getLogger(__name__)


def retry_after_seconds(value: str) -> Optional[float]:
    """Seconds to wait from a ``Retry-After`` header value, or None.

    RFC 9110 allows BOTH forms: delta-seconds (``"17"``) and an HTTP-date
    (``"Wed, 21 Oct 2015 07:28:00 GMT"``) — our own shedding server sends
    the integer form, but proxies and foreign peers routinely send the
    date form, which used to be silently ignored (keeping the computed
    exponential backoff). A date in the past clamps to 0.
    """
    value = value.strip()
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    from email.utils import parsedate_to_datetime

    try:
        when = parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if when is None:
        return None
    from datetime import datetime, timezone

    if when.tzinfo is None:  # RFC 5322 parse of a legacy zone-less date
        when = when.replace(tzinfo=timezone.utc)
    return max(0.0, (when - datetime.now(timezone.utc)).total_seconds())


class HttpUnprocessableEntity(Exception):
    """422 — the endpoint exists but rejected the payload (no point
    retrying)."""


async def fetch_metadata_all(
    session: aiohttp.ClientSession,
    base_url: str,
    project: str,
    deadline: float = 10.0,
    digest: bool = False,
) -> Optional[Dict[str, Any]]:
    """One-request control-plane snapshot from the collection server's
    ``metadata-all`` endpoint, shared by watchman and the bulk client.

    ``digest=True`` asks for the bounded per-target digest instead of
    full metadata (watchman's polling default; the bulk client needs the
    full dataset configs and never sets it).

    Best-effort by contract: returns the validated body (a dict with a
    dict ``targets``) or None on non-200, timeout, or malformed/foreign
    responses — callers fall back to per-target requests. The ``deadline``
    matters because this runs serially BEFORE the fallback: a foreign
    endpoint that accepts the connection but hangs must not stall the
    caller by the full session timeout (or fetch retries)."""
    suffix = "?digest=1" if digest else ""

    async def get():
        async with session.get(
            f"{base_url.rstrip('/')}/gordo/v0/{project}/metadata-all{suffix}"
        ) as resp:
            if resp.status != 200:
                return None
            return await resp.json()

    try:
        # shared deadline helper (resilience/deadline.py): the same
        # bound watchman's scrape/refresh paths use, so every
        # control-plane "give up after" expires identically
        body = await Deadline(deadline).wait_for(get())
    except (aiohttp.ClientError, asyncio.TimeoutError, ValueError) as exc:
        # ValueError covers json.JSONDecodeError on a malformed 200;
        # DeadlineExceeded subclasses asyncio.TimeoutError
        logger.debug("metadata-all fetch failed: %s", exc)
        return None
    if not isinstance(body, dict) or not isinstance(body.get("targets"), dict):
        # a catch-all proxy can 200 unknown paths with arbitrary JSON
        return None
    return body


async def fetch_json(
    session: aiohttp.ClientSession,
    url: str,
    *,
    method: str = "GET",
    json_payload: Optional[Dict[str, Any]] = None,
    data: Optional[bytes] = None,
    headers: Optional[Dict[str, str]] = None,
    retries: int = 3,
    backoff: float = 0.5,
    backoff_cap: float = 60.0,
    retry_budget: Optional[RetryBudget] = None,
    deadline: Optional[Deadline] = None,
    rng: Optional[random.Random] = None,
) -> Dict[str, Any]:
    """GET/POST returning parsed JSON, with bounded retry on transient
    failures; 4xx (except 408/429) are not retried. ``data`` posts a raw
    body (e.g. parquet bytes) with ``headers`` carrying its content type;
    mutually exclusive with ``json_payload``.

    Retry sleeps use decorrelated jitter (never the synchronized
    ``backoff * 2**attempt`` schedule), a server's ``Retry-After`` drain
    estimate still takes precedence as a lower bound, and two optional
    citizenship controls gate the loop:

    - ``retry_budget`` — a shared token bucket
      (:class:`~gordo_components_tpu.resilience.retry_budget.RetryBudget`);
      when it refuses a token the last error raises immediately (fail
      fast: the fleet is already saturated with first-offer load).
    - ``deadline`` — the request's remaining budget: stamped on the wire
      as ``X-Gordo-Deadline-Ms`` (recomputed per attempt so the server
      sees the budget LEFT, not the original), bounding each attempt
      locally, and ending the retry loop once expired.

    ``rng`` pins the jitter stream for deterministic tests.
    """
    if json_payload is not None and data is not None:
        raise ValueError("pass json_payload or data, not both")
    # retries counts TOTAL attempts; clamp so retries=0 ("no retries")
    # still sends the one first offer instead of raising a bare None
    retries = max(1, int(retries))
    if retry_budget is not None:
        retry_budget.note_request()

    async def attempt_once() -> Dict[str, Any]:
        send_headers = headers
        if deadline is not None:
            # per-attempt restamp: the server must see the remaining
            # budget, not the original — a retry arriving with 50ms left
            # of a 2000ms budget must not be queued as if it had 2000ms
            send_headers = dict(headers or {})
            send_headers[DEADLINE_HEADER] = str(
                max(1, int(deadline.remaining_ms()))
            )
        async with session.request(
            method, url, json=json_payload, data=data, headers=send_headers
        ) as resp:
            if resp.status == 422:
                raise HttpUnprocessableEntity(await resp.text())
            if resp.status in (408, 429) or resp.status >= 500:
                raise aiohttp.ClientResponseError(
                    resp.request_info,
                    resp.history,
                    status=resp.status,
                    message=await resp.text(),
                    headers=resp.headers,  # carries Retry-After on 429
                )
            if resp.status >= 400:
                body = await resp.text()
                raise ValueError(f"HTTP {resp.status} from {url}: {body[:500]}")
            if resp.content_type == TENSOR_CONTENT_TYPE:
                # binary scoring response (the framed tensor wire format,
                # utils/wire.py): hand the raw body back — the caller
                # owns the decode, exactly as it owns the JSON schema
                return await resp.read()
            return await resp.json()

    last_exc: Optional[Exception] = None
    prev_delay = backoff
    for attempt in range(retries):
        try:
            if deadline is not None:
                if deadline.expired():
                    raise DeadlineExceeded(
                        f"deadline expired before attempt {attempt + 1} "
                        f"to {url}"
                    )
                return await deadline.wait_for(attempt_once())
            return await attempt_once()
        except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
            last_exc = exc
            if attempt + 1 >= retries:
                break  # no retry left: sleeping first would only delay the error
            if deadline is not None and deadline.expired():
                break  # out of time: a retry would expire server-side anyway
            if retry_budget is not None and not retry_budget.try_spend():
                logger.warning(
                    "Request %s %s failed (%s); retry budget exhausted — "
                    "failing fast instead of re-offering load",
                    method, url, exc,
                )
                break
            # decorrelated jitter: chunks that failed together must NOT
            # retry together (a deterministic schedule re-creates the
            # overload it backed off from, wave after wave)
            delay = prev_delay = decorrelated_jitter(
                backoff, prev_delay, cap=backoff_cap, rng=rng
            )
            # a shedding server's Retry-After is its queue-drain estimate
            # (server/bank.py EngineOverloaded): honoring it beats blind
            # backoff — the fleet-backfill storm re-offers load right
            # when capacity frees instead of too early (more sheds) or
            # too late (idle server). Both header forms parse
            # (delta-seconds and HTTP-date — proxies send the latter).
            # Clamped: the value is server/proxy-controlled, and a huge
            # or inf value must not hang the backfill
            if (
                isinstance(exc, aiohttp.ClientResponseError)
                and exc.headers is not None
                and exc.headers.get("Retry-After")
            ):
                hinted = retry_after_seconds(exc.headers["Retry-After"])
                if hinted is not None:
                    delay = max(delay, min(hinted, 60.0))
            if deadline is not None:
                # never sleep past our own expiry: a dead chunk holding
                # its concurrency slot through a 30s Retry-After nap is
                # capacity stolen from chunks that could still succeed
                delay = min(delay, deadline.remaining_s())
            logger.warning(
                "Request %s %s failed (%s); retry %d/%d in %.1fs",
                method, url, exc, attempt + 1, retries, delay,
            )
            await asyncio.sleep(delay)
    raise last_exc  # type: ignore[misc]


async def fetch_json_hedged(
    session: aiohttp.ClientSession,
    urls: List[str],
    *,
    hedge_delay_s: float,
    hedge_stats: Optional[Dict[str, int]] = None,
    **kwargs: Any,
) -> Dict[str, Any]:
    """Tail-latency hedging: issue the request to ``urls[0]``; if it
    hasn't answered within ``hedge_delay_s`` (derive it from the
    observed p95 so only the slowest ~5% of requests hedge), issue ONE
    duplicate to ``urls[1]`` and return the first success, cancelling
    the loser. A single-entry ``urls`` degrades to plain
    :func:`fetch_json`.

    ``hedge_stats`` (optional dict) gets ``hedges``/``hedge_wins``
    incremented — the bulk client exposes them as
    ``gordo_client_hedges_total``/``gordo_client_hedge_wins_total``.
    Both failing raises the PRIMARY's error (the hedge is an
    optimization; its replica's failure mode is secondary information,
    logged at DEBUG).
    """
    if len(urls) < 2:
        return await fetch_json(session, urls[0], **kwargs)
    primary = asyncio.ensure_future(fetch_json(session, urls[0], **kwargs))
    try:
        return await asyncio.wait_for(asyncio.shield(primary), hedge_delay_s)
    except asyncio.TimeoutError:
        pass  # primary still in flight: hedge it
    except BaseException:
        # primary FAILED fast (an error, not slowness) — or the CALLER
        # was cancelled mid-wait: either way the shielded task must not
        # keep running unawaited against the server
        primary.cancel()
        raise
    if hedge_stats is not None:
        hedge_stats["hedges"] = hedge_stats.get("hedges", 0) + 1
    # the hedge is a ONE-shot rescue: no internal retries, and no
    # note_request deposit into the shared budget — a hedge is extra
    # offered load, and letting it earn retry tokens would quietly
    # loosen the documented 1+ratio re-offer cap exactly in the
    # high-hedge-rate overload regime the budget protects against
    hedge_kwargs = {**kwargs, "retries": 1, "retry_budget": None}
    hedge = asyncio.ensure_future(fetch_json(session, urls[1], **hedge_kwargs))
    pending = {primary, hedge}
    first_exc: Optional[BaseException] = None
    try:
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                exc = task.exception()
                if exc is None:
                    if task is hedge and hedge_stats is not None:
                        hedge_stats["hedge_wins"] = (
                            hedge_stats.get("hedge_wins", 0) + 1
                        )
                    return task.result()
                if task is primary:
                    first_exc = exc
                else:
                    logger.debug("hedge request to %s failed: %s", urls[1], exc)
                    if first_exc is None:
                        first_exc = exc
    finally:
        for task in pending:  # cancel the loser
            task.cancel()
    raise first_exc  # type: ignore[misc]  # both failed
