"""Async HTTP helpers for the bulk client.

Reference parity: gordo_components/client/io.py (unverified; SURVEY.md §2
"client") — bounded-concurrency POSTs with retry/backoff.
"""

import asyncio
import logging
from typing import Any, Dict, Optional

import aiohttp

logger = logging.getLogger(__name__)


def retry_after_seconds(value: str) -> Optional[float]:
    """Seconds to wait from a ``Retry-After`` header value, or None.

    RFC 9110 allows BOTH forms: delta-seconds (``"17"``) and an HTTP-date
    (``"Wed, 21 Oct 2015 07:28:00 GMT"``) — our own shedding server sends
    the integer form, but proxies and foreign peers routinely send the
    date form, which used to be silently ignored (keeping the computed
    exponential backoff). A date in the past clamps to 0.
    """
    value = value.strip()
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    from email.utils import parsedate_to_datetime

    try:
        when = parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if when is None:
        return None
    from datetime import datetime, timezone

    if when.tzinfo is None:  # RFC 5322 parse of a legacy zone-less date
        when = when.replace(tzinfo=timezone.utc)
    return max(0.0, (when - datetime.now(timezone.utc)).total_seconds())


class HttpUnprocessableEntity(Exception):
    """422 — the endpoint exists but rejected the payload (no point
    retrying)."""


async def fetch_metadata_all(
    session: aiohttp.ClientSession,
    base_url: str,
    project: str,
    deadline: float = 10.0,
    digest: bool = False,
) -> Optional[Dict[str, Any]]:
    """One-request control-plane snapshot from the collection server's
    ``metadata-all`` endpoint, shared by watchman and the bulk client.

    ``digest=True`` asks for the bounded per-target digest instead of
    full metadata (watchman's polling default; the bulk client needs the
    full dataset configs and never sets it).

    Best-effort by contract: returns the validated body (a dict with a
    dict ``targets``) or None on non-200, timeout, or malformed/foreign
    responses — callers fall back to per-target requests. The ``deadline``
    matters because this runs serially BEFORE the fallback: a foreign
    endpoint that accepts the connection but hangs must not stall the
    caller by the full session timeout (or fetch retries)."""
    suffix = "?digest=1" if digest else ""

    async def get():
        async with session.get(
            f"{base_url.rstrip('/')}/gordo/v0/{project}/metadata-all{suffix}"
        ) as resp:
            if resp.status != 200:
                return None
            return await resp.json()

    try:
        body = await asyncio.wait_for(get(), timeout=deadline)
    except (aiohttp.ClientError, asyncio.TimeoutError, ValueError) as exc:
        # ValueError covers json.JSONDecodeError on a malformed 200
        logger.debug("metadata-all fetch failed: %s", exc)
        return None
    if not isinstance(body, dict) or not isinstance(body.get("targets"), dict):
        # a catch-all proxy can 200 unknown paths with arbitrary JSON
        return None
    return body


async def fetch_json(
    session: aiohttp.ClientSession,
    url: str,
    *,
    method: str = "GET",
    json_payload: Optional[Dict[str, Any]] = None,
    data: Optional[bytes] = None,
    headers: Optional[Dict[str, str]] = None,
    retries: int = 3,
    backoff: float = 0.5,
) -> Dict[str, Any]:
    """GET/POST returning parsed JSON, with bounded retry on transient
    failures; 4xx (except 408/429) are not retried. ``data`` posts a raw
    body (e.g. parquet bytes) with ``headers`` carrying its content type;
    mutually exclusive with ``json_payload``."""
    if json_payload is not None and data is not None:
        raise ValueError("pass json_payload or data, not both")
    last_exc: Optional[Exception] = None
    for attempt in range(retries):
        try:
            async with session.request(
                method, url, json=json_payload, data=data, headers=headers
            ) as resp:
                if resp.status == 422:
                    raise HttpUnprocessableEntity(await resp.text())
                if resp.status in (408, 429) or resp.status >= 500:
                    raise aiohttp.ClientResponseError(
                        resp.request_info,
                        resp.history,
                        status=resp.status,
                        message=await resp.text(),
                        headers=resp.headers,  # carries Retry-After on 429
                    )
                if resp.status >= 400:
                    body = await resp.text()
                    raise ValueError(f"HTTP {resp.status} from {url}: {body[:500]}")
                return await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
            last_exc = exc
            if attempt + 1 >= retries:
                break  # no retry left: sleeping first would only delay the error
            delay = backoff * (2**attempt)
            # a shedding server's Retry-After is its queue-drain estimate
            # (server/bank.py EngineOverloaded): honoring it beats blind
            # exponential backoff — the fleet-backfill storm re-offers
            # load right when capacity frees instead of too early (more
            # sheds) or too late (idle server). Both header forms parse
            # (delta-seconds and HTTP-date — proxies send the latter).
            # Clamped: the value is server/proxy-controlled, and a huge or
            # inf value must not hang the backfill
            if (
                isinstance(exc, aiohttp.ClientResponseError)
                and exc.headers is not None
                and exc.headers.get("Retry-After")
            ):
                hinted = retry_after_seconds(exc.headers["Retry-After"])
                if hinted is not None:
                    delay = max(delay, min(hinted, 60.0))
            logger.warning(
                "Request %s %s failed (%s); retry %d/%d in %.1fs",
                method, url, exc, attempt + 1, retries, delay,
            )
            await asyncio.sleep(delay)
    raise last_exc  # type: ignore[misc]
