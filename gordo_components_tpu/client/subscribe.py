"""Push-mode subscriber: the client half of ``GET .../results/stream``.

The server side (score-on-ingest push, ``GORDO_PUSH=1``) parks long-poll
requests and answers with every window scored since the subscriber's
last poll. This module owns the loop a consumer actually runs: poll,
deliver, reconnect. The one behavior that matters at fleet scale is the
RECONNECT schedule — when a replica restarts (or chaos resets its
connections), every subscriber's long-poll fails at the same instant,
and reconnecting immediately turns one replica blip into a thundering
herd against the freshly-restarted process. Reconnects here sleep a
decorrelated-jitter delay (``resilience/retry_budget.decorrelated_jitter``
— same schedule the scoring path's retries use), so a herd of
subscribers de-synchronizes itself after one failed poll each.

The mesh game-day harness drives exactly this scenario
(``thundering_herd`` in ``gameday/scenarios.py``) and judges the spread.
"""

import asyncio
import logging
import random
from typing import Any, Callable, Dict, List, Optional

from gordo_components_tpu.resilience.retry_budget import decorrelated_jitter

logger = logging.getLogger(__name__)

__all__ = ["PushSubscriber"]


class PushSubscriber:
    """Long-poll consumer for one target's scored-window stream.

    ``base_url`` may be ``""`` when ``session`` already carries the base
    (aiohttp's test client), or the replica base URL for a real session.
    ``rng`` seeds the jitter schedule (seeded = a replayable game day);
    each subscriber should get its OWN rng — sharing one defeats the
    point of decorrelation exactly when it matters.
    """

    def __init__(
        self,
        base_url: str,
        project: str,
        target: str,
        *,
        subscriber: Optional[str] = None,
        poll_timeout_s: float = 10.0,
        reconnect_base_s: float = 0.05,
        reconnect_cap_s: float = 5.0,
        rng: Optional[random.Random] = None,
    ):
        self.base_url = (base_url or "").rstrip("/")
        self.project = project
        self.target = target
        self.subscriber = subscriber
        self.poll_timeout_s = float(poll_timeout_s)
        self.reconnect_base_s = float(reconnect_base_s)
        self.reconnect_cap_s = float(reconnect_cap_s)
        self._rng = rng
        self._prev_delay = self.reconnect_base_s
        self.results: List[Any] = []
        self.stats: Dict[str, int] = {
            "polls": 0, "failures": 0, "reconnects": 0, "dropped": 0,
        }
        # every jittered reconnect delay, in order — the game-day judge
        # reads this to assert the herd actually spread out
        self.reconnect_delays: List[float] = []
        self.last_status: Optional[int] = None

    @property
    def url(self) -> str:
        return (
            f"{self.base_url}/gordo/v0/{self.project}/{self.target}"
            "/results/stream"
        )

    async def poll_once(self, session) -> List[Any]:
        """One long-poll round trip. Returns the (possibly empty) batch
        of scored windows; raises on transport failure or a non-200 —
        the caller's reconnect schedule owns what happens next."""
        params: Dict[str, Any] = {"timeout": str(self.poll_timeout_s)}
        if self.subscriber:
            params["subscriber"] = self.subscriber
        async with session.get(self.url, params=params) as resp:
            self.last_status = resp.status
            if resp.status != 200:
                raise ConnectionError(
                    f"results/stream answered {resp.status} for "
                    f"{self.target!r}"
                )
            body = await resp.json()
        # the server mints an id on the first anonymous poll and echoes
        # it — keep it, or every poll would re-register a new subscriber
        self.subscriber = body.get("subscriber") or self.subscriber
        self.stats["polls"] += 1
        self.stats["dropped"] += int(body.get("dropped") or 0)
        batch = body.get("results") or []
        self.results.extend(batch)
        return batch

    async def run(
        self,
        session,
        *,
        stop: Optional[asyncio.Event] = None,
        max_polls: Optional[int] = None,
        on_results: Optional[Callable[[List[Any]], None]] = None,
    ) -> Dict[str, int]:
        """Poll until ``stop`` is set (or ``max_polls`` successful
        polls). A failed poll — replica restarting, connection reset,
        push table momentarily full — sleeps a decorrelated-jitter delay
        and reconnects; a successful poll resets the schedule to its
        base, so a healthy stream pays no backoff."""
        while (stop is None or not stop.is_set()) and (
            max_polls is None or self.stats["polls"] < max_polls
        ):
            try:
                batch = await self.poll_once(session)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                self.stats["failures"] += 1
                if stop is not None and stop.is_set():
                    break
                delay = decorrelated_jitter(
                    self.reconnect_base_s,
                    self._prev_delay,
                    cap=self.reconnect_cap_s,
                    rng=self._rng,
                )
                self._prev_delay = delay
                self.reconnect_delays.append(delay)
                self.stats["reconnects"] += 1
                logger.debug(
                    "subscriber %s poll failed (%s); reconnecting in %.3fs",
                    self.subscriber or "<anon>", exc, delay,
                )
                await asyncio.sleep(delay)
                continue
            self._prev_delay = self.reconnect_base_s
            if batch and on_results is not None:
                on_results(batch)
        return dict(self.stats)
