"""Bulk-prediction client (reference parity: gordo_components/client/,
unverified — SURVEY.md §2)."""

from gordo_components_tpu.client.client import Client, PredictionResult
from gordo_components_tpu.client.forwarders import (
    ForwardPredictionsIntoInflux,
    ForwardPredictionsIntoParquet,
)
from gordo_components_tpu.client.subscribe import PushSubscriber

__all__ = [
    "Client",
    "PredictionResult",
    "ForwardPredictionsIntoInflux",
    "ForwardPredictionsIntoParquet",
    "PushSubscriber",
]
