"""Prediction forwarders.

Reference parity: ``ForwardPredictionsIntoInflux``
(gordo_components/client/forwarders.py, unverified; SURVEY.md §2 "client")
— write prediction/anomaly frames back to a store. The InfluxDB wire client
is not in this image, so the Influx forwarder accepts an injected client;
a filesystem (parquet) forwarder is provided as the batteries-included
store for TPU-pod-local runs.
"""

import logging
import os
from typing import Any, Optional

logger = logging.getLogger(__name__)


class ForwardPredictionsIntoInflux:
    """Write each machine's prediction frame as InfluxDB points.

    ``client``: injected object with ``write_points(points, ...)``
    (e.g. ``influxdb.InfluxDBClient``); required since the influxdb package
    is unavailable here.
    """

    def __init__(
        self,
        client: Any = None,
        destination_measurement: str = "predictions",
        value_name: str = "value",
    ):
        if client is None:
            raise ValueError(
                "InfluxDB client package unavailable — pass client= (object "
                "with write_points)."
            )
        self.client = client
        self.destination_measurement = destination_measurement
        self.value_name = value_name

    def forward(self, result) -> None:
        df = result.predictions
        points = []
        for ts, row in df.iterrows():
            for col, value in row.items():
                field = "|".join(c for c in col if c) if isinstance(col, tuple) else str(col)
                points.append(
                    {
                        "measurement": self.destination_measurement,
                        "tags": {"machine": result.name, "field": field},
                        "time": str(ts),
                        "fields": {self.value_name: float(value)},
                    }
                )
        logger.info("Forwarding %d points for %s to influx", len(points), result.name)
        self.client.write_points(points)


class ForwardPredictionsIntoParquet:
    """Write each machine's prediction frame to
    ``<root>/<machine>.parquet`` (TPU-native default store)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def forward(self, result) -> None:
        from gordo_components_tpu.utils.encoding import parquet_engine

        path = os.path.join(self.root, f"{result.name}.parquet")
        df = result.predictions
        if hasattr(df.columns, "to_flat_index"):
            # shallow copy shares the data blocks (verified with
            # np.shares_memory) and only the column labels are replaced —
            # the old deep .copy() duplicated the whole backfill frame
            # just to rename columns for the parquet writer
            df = df.copy(deep=False)
            df.columns = [
                "|".join(c for c in col if c)
                if isinstance(col, tuple)
                else str(col)
                for col in df.columns.to_flat_index()
            ]
        df.to_parquet(path, engine=parquet_engine() or "auto")
        logger.info("Wrote predictions for %s -> %s", result.name, path)
