"""Time-major sequence fast path: scan-over-time with the member axis
innermost.

The fleet engine's original recurrent layout nests ``vmap`` (member axis)
OUTSIDE ``flax.linen.RNN`` (``lax.scan`` inside): every scan step issues M
interleaved small matmuls whose lane dimension is one member's hidden
width. The TPU bench (BENCH_TPU_20260731) measured that layout at 0.5x the
per-model throughput of training members one at a time — vmap-over-members
is a *pessimization* for recurrent architectures.

This module inverts the nesting. One ``lax.scan`` over time; the carry and
activations keep members as the INNERMOST (lane-friendly) axis:

- inputs arrive member-major ``(M, B, T, F)`` (the fleet's stacking order)
  and are transposed ONCE to time-major ``(T, B, M, F)``;
- the input projection for ALL timesteps is hoisted out of the scan as one
  wide einsum per layer (``tbmf,mfg->tbmg``);
- each scan step is a single batched matmul ``bmh,mhg->bmg`` plus the gate
  nonlinearities and carry update.

Weight extraction targets ``flax.linen.OptimizedLSTMCell``'s param tree
(separate per-gate kernels ``ii/if/ig/io`` and ``hi/hf/hg/ho``, bias on the
hidden half only); gate math is the flax cell's exactly::

    z = x @ Wi + h @ Wh + b          # gate order i, f, g, o
    c' = sigmoid(f) * c + sigmoid(i) * tanh(g)
    h' = sigmoid(o) * tanh(c')

so the time-major forward matches ``vmap(module.apply)`` to fp32 rounding
(matmul re-association only — the parity band is pinned by
tests/test_seq_fastpath.py).

Two env knobs, resolved ONCE per compiled program (never per call):

- ``GORDO_SEQ_LAYOUT`` = ``auto|time_major|legacy``. ``auto`` picks
  ``time_major`` on TPU/GPU backends and ``legacy`` on CPU: the layout win
  is a lane-utilization effect, and keeping single-device CPU on the
  legacy path preserves the byte-for-byte fleet-vs-single guarantees the
  CPU test suite pins (tests opt in explicitly).
- ``GORDO_SEQ_KERNEL`` = ``auto|pallas|interpret|jnp``: the fused
  recurrent-step kernel below (gate matmul + nonlinearities + carry update
  in one VMEM pass per step), ``GORDO_BANK_KERNEL``-style resolution with
  interpret mode as CI's parity vehicle. The kernel is FORWARD-ONLY: it
  serves the bank's compiled scoring programs; training keeps the jnp step
  (its backward comes from autodiff through the scan — a custom VJP for
  the fused step is future work, see docs/architecture.md).
"""

import functools
import logging
import os

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

SEQ_LAYOUT_ENV = "GORDO_SEQ_LAYOUT"
SEQ_KERNEL_ENV = "GORDO_SEQ_KERNEL"
_SEQ_LAYOUTS = ("auto", "time_major", "legacy")
_SEQ_KERNEL_MODES = ("auto", "pallas", "interpret", "jnp")

_GATES = ("i", "f", "g", "o")  # flax OptimizedLSTMCell split order
LANE = 128  # TPU lane width (f32)
SUBLANE = 8


def _fast_backend() -> bool:
    return jax.default_backend() in ("tpu", "gpu")


def resolve_seq_layout(mode: str = None) -> str:
    """Concrete layout for sequence fleet programs: ``mode`` (or env
    ``GORDO_SEQ_LAYOUT``, default ``auto``) resolved against the backend.
    Resolved once per program build — the layout is baked into the
    bucket's compiled epoch/scoring program, not re-decided per call."""
    raw = (mode or os.environ.get(SEQ_LAYOUT_ENV) or "auto").strip().lower()
    if raw not in _SEQ_LAYOUTS:
        raise ValueError(
            f"{SEQ_LAYOUT_ENV} must be one of {'|'.join(_SEQ_LAYOUTS)}, "
            f"got {raw!r}"
        )
    if raw == "auto":
        return "time_major" if _fast_backend() else "legacy"
    return raw


_step_probe_ok = None


def _probe_step_kernel() -> bool:
    """One tiny compile of the fused step, cached per process — the
    recurrent analogue of pallas_score's banked probe: auto mode must
    never bake a kernel that cannot compile into a scoring program."""
    global _step_probe_ok
    if _step_probe_ok is None:
        try:
            out = fused_lstm_step(
                jnp.zeros((8, 1, 4 * LANE), jnp.float32),
                jnp.zeros((8, 1, LANE), jnp.float32),
                jnp.zeros((8, 1, LANE), jnp.float32),
                jnp.zeros((1, LANE, 4 * LANE), jnp.float32),
                jnp.zeros((1, 4 * LANE), jnp.float32),
            )
            jax.block_until_ready(out)
            _step_probe_ok = True
        except Exception:
            _step_probe_ok = False
            logger.warning(
                "Fused LSTM-step Pallas kernel failed to compile on backend "
                "%r; scoring programs built in auto mode use the jnp step "
                "for the rest of this process (GORDO_SEQ_KERNEL=pallas to "
                "surface the error)",
                jax.default_backend(),
                exc_info=True,
            )
    return _step_probe_ok


def resolve_seq_kernel_mode(mode: str = None) -> str:
    """Dispatch mode for the fused recurrent-step kernel (scoring path):
    ``mode`` (or env ``GORDO_SEQ_KERNEL``, default ``auto``) resolved once
    per program build. ``auto`` on TPU probe-compiles first and degrades
    to jnp if the probe fails; an explicit ``pallas`` never degrades."""
    raw = (mode or os.environ.get(SEQ_KERNEL_ENV) or "auto").strip().lower()
    if raw not in _SEQ_KERNEL_MODES:
        raise ValueError(
            f"{SEQ_KERNEL_ENV} must be one of {'|'.join(_SEQ_KERNEL_MODES)}, "
            f"got {raw!r}"
        )
    if raw == "auto":
        return (
            "pallas"
            if jax.default_backend() == "tpu" and _probe_step_kernel()
            else "jnp"
        )
    return raw


def supports_time_major(module) -> bool:
    """Duck-typed: the time-major forward understands exactly the
    LSTMStack architecture (per-layer ``OptimizedLSTMCell`` + elementwise
    activation, final-step Dense head). Anything else — conv (no
    recurrence; its fast path is the matmul formulation), VAE heads,
    custom modules — stays on the legacy layout."""
    return all(
        hasattr(module, a) for a in ("dims", "funcs", "out_func", "n_features")
    ) and not hasattr(module, "channels")


def extract_lstm_weights(module, params):
    """Per-layer ``(Wi, Wh, b)`` + Dense head from an LSTMStack param tree.

    Works on a single tree or a member-stacked one (leading M axis on
    every leaf): per-gate kernels concatenate on the LAST axis in flax's
    ``i, f, g, o`` split order, so each gate's output columns are the
    same dot products the cell computes — parity is limited only by
    accumulation order.

    Returns ``(layers, (Wd, bd))`` with ``layers[l] = (Wi, Wh, b)`` of
    shapes ``([M,] F_in, 4H)``, ``([M,] H, 4H)``, ``([M,] 4H)``.
    """
    p = params["params"] if "params" in params else params
    layers = []
    for l in range(len(module.dims)):
        cell = p[f"OptimizedLSTMCell_{l}"]
        Wi = jnp.concatenate(
            [cell[f"i{g}"]["kernel"] for g in _GATES], axis=-1
        )
        Wh = jnp.concatenate(
            [cell[f"h{g}"]["kernel"] for g in _GATES], axis=-1
        )
        b = jnp.concatenate([cell[f"h{g}"]["bias"] for g in _GATES], axis=-1)
        layers.append((Wi, Wh, b))
    head = p["Dense_0"]
    return layers, (head["kernel"], head["bias"])


def _lstm_gates(z, c):
    """flax OptimizedLSTMCell carry update from the fused gate block."""
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
    return c2, h2


def lstm_step_jnp(xz_t, h, c, Wh, b):
    """One recurrent step, member axis innermost. xz_t: (B, M, 4H)
    precomputed input projection; h/c: (B, M, H); Wh: (M, H, 4H);
    b: (M, 4H). Returns (c', h')."""
    z = xz_t + jnp.einsum("bmh,mhg->bmg", h, Wh) + b[None]
    return _lstm_gates(z, c)


# ------------------------------------------------------------------ #
# Fused recurrent-step Pallas kernel (forward/scoring only)
# ------------------------------------------------------------------ #


def _step_kernel(xz_ref, h_ref, c_ref, wh_ref, b_ref, c2_ref, h2_ref):
    """Grid step = one member: gate matmul + nonlinearities + carry update
    in a single VMEM pass — the recurrent analogue of pallas_score's
    banked grid. Blocks carry a singleton member axis (B, 1, ·)."""
    z = (
        xz_ref[:, 0, :]
        + jnp.dot(h_ref[:, 0, :], wh_ref[0], preferred_element_type=jnp.float32)
        + b_ref[0][None, :]
    )
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c = c_ref[:, 0, :]
    c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
    c2_ref[:, 0, :] = c2
    h2_ref[:, 0, :] = h2


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_lstm_step(xz_t, h, c, Wh, b, interpret: bool = False):
    """Pallas fused step with the same signature/layout as
    :func:`lstm_step_jnp` (member axis innermost, H already padded to the
    lane tile by :func:`pad_gate_lanes`). Returns (c', h')."""
    from jax.experimental import pallas as pl

    B, M, H4 = xz_t.shape
    H = H4 // 4
    grid = (M,)
    blk_h = pl.BlockSpec((B, 1, H), lambda m: (0, m, 0))
    blk_z = pl.BlockSpec((B, 1, H4), lambda m: (0, m, 0))
    return pl.pallas_call(
        _step_kernel,
        grid=grid,
        in_specs=[
            blk_z,
            blk_h,
            blk_h,
            pl.BlockSpec((1, H, H4), lambda m: (m, 0, 0)),
            pl.BlockSpec((1, H4), lambda m: (m, 0)),
        ],
        out_specs=[blk_h, blk_h],
        out_shape=[
            jax.ShapeDtypeStruct((B, M, H), xz_t.dtype),
            jax.ShapeDtypeStruct((B, M, H), xz_t.dtype),
        ],
        interpret=interpret,
    )(xz_t, h, c, Wh, b)


def _round_up(n: int, k: int) -> int:
    return -(-n // k) * k


def pad_gate_lanes(Wh, b, H: int, Hp: int):
    """Pad the hidden width to the lane tile GATE-ALIGNED: the fused gate
    block splits into four H-wide slices, so padding must go inside each
    gate's slice (zero kernel columns/rows and zero bias), not at the
    end. Padded lanes stay self-contained: their z is exactly 0, the
    resulting 0.5-sigmoid garbage multiplies only zero Wh rows on the
    next step, and the caller slices them off the final hidden state."""
    if Hp == H:
        return Wh, b
    pad_in = Hp - H

    def per_gate(a, axis):
        parts = jnp.split(a, 4, axis=-1)
        widths = [(0, 0)] * a.ndim
        widths[-1] = (0, pad_in)
        parts = [jnp.pad(x, widths) for x in parts]
        return jnp.concatenate(parts, axis=-1)

    Wh = per_gate(Wh, -1)
    rw = [(0, 0)] * Wh.ndim
    rw[-2] = (0, pad_in)
    Wh = jnp.pad(Wh, rw)
    b = per_gate(b, -1)
    return Wh, b


# ------------------------------------------------------------------ #
# Full time-major forward
# ------------------------------------------------------------------ #


def _lstm_layer(x, Wi, Wh, b, kernel: str):
    """One LSTM layer over time-major x: (T, B, M, F_in) -> (T, B, M, H).

    The input projection for ALL timesteps is one wide einsum hoisted out
    of the scan; each scan step is then a single batched matmul + gates.
    """
    T, B, M, _ = x.shape
    H = Wh.shape[-2]
    xz = jnp.einsum("tbmf,mfg->tbmg", x, Wi)
    if kernel in ("pallas", "interpret"):
        Hp = _round_up(H, LANE)
        Whp, bp = pad_gate_lanes(Wh, b, H, Hp)
        Bp = _round_up(B, SUBLANE)
        if Hp != H:
            parts = jnp.split(xz, 4, axis=-1)
            parts = [
                jnp.pad(p, ((0, 0), (0, 0), (0, 0), (0, Hp - H)))
                for p in parts
            ]
            xz = jnp.concatenate(parts, axis=-1)
        if Bp != B:
            xz = jnp.pad(xz, ((0, 0), (0, Bp - B), (0, 0), (0, 0)))
        interpret = kernel == "interpret"

        def step(carry, xz_t):
            c, h = carry
            c2, h2 = fused_lstm_step(xz_t, h, c, Whp, bp, interpret=interpret)
            return (c2, h2), h2

        zeros = jnp.zeros((Bp, M, Hp), x.dtype)
        _, ys = jax.lax.scan(step, (zeros, zeros), xz)
        return ys[:, :B, :, :H]

    def step(carry, xz_t):
        c, h = carry
        c2, h2 = lstm_step_jnp(xz_t, h, c, Wh, b)
        return (c2, h2), h2

    zeros = jnp.zeros((B, M, H), x.dtype)
    _, ys = jax.lax.scan(step, (zeros, zeros), xz)
    return ys


def lstm_time_major_forward(module, stacked_params, xb, kernel: str = "jnp"):
    """Time-major LSTMStack forward over member-stacked params.

    ``xb``: (M, B, T, F) — each member's batch of windows (the fleet's
    stacking order; the bank's scoring path passes (slots, windows, L, F)).
    Returns (M, B, F) predictions matching ``vmap(module.apply)`` to fp32
    rounding. ``kernel`` must already be RESOLVED (jnp|pallas|interpret) —
    training callers pass "jnp" (the fused kernel is forward-only)."""
    from gordo_components_tpu.models.factories.feedforward import (
        resolve_activation,
    )

    dtype = jnp.dtype(getattr(module, "compute_dtype", "float32"))
    layers, (Wd, bd) = extract_lstm_weights(module, stacked_params)
    x = jnp.transpose(xb, (2, 1, 0, 3)).astype(dtype)  # (T, B, M, F)
    for (Wi, Wh, b), func in zip(layers, module.funcs):
        x = _lstm_layer(
            x, Wi.astype(dtype), Wh.astype(dtype), b.astype(dtype), kernel
        )
        x = resolve_activation(func)(x)
    h_last = x[-1]  # (B, M, H) — final hidden state of the last layer
    out = jnp.einsum("bmh,mhf->bmf", h_last, Wd.astype(dtype))
    out = resolve_activation(module.out_func)(out + bd.astype(dtype)[None])
    return jnp.transpose(out, (1, 0, 2)).astype(jnp.float32)
