"""Pure-JAX functional primitives shared by the single-model estimators and
the many-model fleet engine.

Everything in this package is a pure function over explicit parameter
pytrees — no hidden state — so every op is `jit`-able, `vmap`-able over a
leading model axis (the fleet engine's core trick), and shardable with
`shard_map`. This replaces the reference's reliance on sklearn/Keras
stateful objects for the on-device compute path.
"""

from gordo_components_tpu.ops.scaler import (
    ScalerParams,
    fit_minmax,
    fit_standard,
    identity_scaler,
    scaler_inverse_transform,
    scaler_transform,
)
from gordo_components_tpu.ops.windows import sliding_windows, num_windows
from gordo_components_tpu.ops.losses import mse_loss, explained_variance

__all__ = [
    "ScalerParams",
    "fit_minmax",
    "fit_standard",
    "identity_scaler",
    "scaler_transform",
    "scaler_inverse_transform",
    "sliding_windows",
    "num_windows",
    "mse_loss",
    "explained_variance",
]
