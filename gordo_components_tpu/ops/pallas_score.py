"""Fused anomaly-scoring epilogue as a Pallas TPU kernel.

The server's per-request hot loop (SURVEY.md §3.2; reference:
``DiffBasedAnomalyDetector.anomaly``) ends in an elementwise epilogue over
the reconstruction: ``|target - output|``, the per-feature error scaling,
and two row norms. As four separate XLA ops this reads the (rows, F)
operands from HBM several times and writes four results back; the Pallas
kernel streams each row tile through VMEM exactly once and emits all four
outputs from that single pass — one HBM read per operand, four writes,
zero intermediate round-trips.

Usage is transparent: :func:`fused_anomaly_score` dispatches to the kernel
on TPU backends and to an identical pure-jnp implementation elsewhere
(tests run it in interpreter mode via ``interpret=True`` to exercise the
kernel logic on CPU). Feature/row padding to hardware tiles (8 sublanes x
128 lanes for f32) happens in the wrapper; padded feature lanes are masked
inside the kernel so they contribute nothing to the scaled errors or the
norms.

Two entry points share the kernel math:

- :func:`fused_anomaly_score` — the *per-model* path
  (``DiffBasedAnomalyDetector.anomaly``: single model, one (rows, F)
  request), auto-dispatching per call.
- :func:`banked_anomaly_score` — the *banked* serving path
  (server/bank.py): a batched grid over (member, row-tile) that gathers
  each batch slot's per-member error-scaler vectors via scalar-prefetch
  indices and runs scale → reconstruction-error → row norms in one VMEM
  pass over the whole coalesced batch. It is traced INSIDE the bank's
  per-bucket jit program, so the dispatch decision (``mode``) is made
  once at bucket-finalize time — ``resolve_bank_kernel_mode`` reads
  ``GORDO_BANK_KERNEL`` (auto|pallas|interpret|jnp; auto = kernel on
  TPU, jnp elsewhere).

Error budget (the parity harness in tests/test_banked_kernel.py pins
this): the elementwise outputs (``diff``, ``scaled``) are BITWISE equal
to the jnp reference at fp32 — they never cross a reduction. The two
row norms reduce over the 128-lane padded feature axis, whose tree
order can differ from the unpadded jnp sum when ``F`` is not a lane
multiple: observed ≤2 ULP, asserted ≤4 ULP.
"""

import functools
import logging
import os
from typing import Tuple

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

ROW_TILE = 256  # rows per grid step (multiple of the 8-sublane f32 tile)
LANE = 128


@jax.jit
def _jnp_score(target, output, shift, scale):
    """Reference implementation (also the non-TPU fallback)."""
    diff = jnp.abs(target - output)
    scaled = (diff - shift) * scale
    tot_u = jnp.sqrt(jnp.sum(diff * diff, axis=-1))
    tot_s = jnp.sqrt(jnp.sum(scaled * scaled, axis=-1))
    return diff, scaled, tot_u, tot_s


def _kernel(n_features: int, t_ref, o_ref, shift_ref, scale_ref,
            diff_ref, scaled_ref, tu_ref, ts_ref):
    t = t_ref[:]
    o = o_ref[:]
    diff = jnp.abs(t - o)
    # feature lanes beyond n_features are padding: zero them so the scaled
    # error's affine shift doesn't leak into the norms
    mask = jax.lax.broadcasted_iota(jnp.int32, diff.shape, 1) < n_features
    diff = jnp.where(mask, diff, 0.0)
    scaled = jnp.where(mask, (diff - shift_ref[:]) * scale_ref[:], 0.0)
    diff_ref[:] = diff
    scaled_ref[:] = scaled
    tu_ref[:] = jnp.sqrt(jnp.sum(diff * diff, axis=1, keepdims=True))
    ts_ref[:] = jnp.sqrt(jnp.sum(scaled * scaled, axis=1, keepdims=True))


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_score(target, output, shift, scale, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, F = target.shape
    Fp = -(-F // LANE) * LANE
    # adaptive row tile: small requests shouldn't pad to a full ROW_TILE
    # (a 33-row request tiles at 40, not 256); multiples of the 8-sublane
    # f32 tile keep the hardware layout happy
    row_tile = min(ROW_TILE, -(-rows // 8) * 8)
    Rp = -(-rows // row_tile) * row_tile

    pad2 = lambda a: jnp.pad(a, ((0, Rp - rows), (0, Fp - F)))
    t = pad2(target.astype(jnp.float32))
    o = pad2(output.astype(jnp.float32))
    row_vec = lambda v: jnp.pad(v.astype(jnp.float32), (0, Fp - F))[None, :]
    sh, sc = row_vec(shift), row_vec(scale)

    grid = (Rp // row_tile,)
    tile = lambda: pl.BlockSpec(
        (row_tile, Fp), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    const = lambda: pl.BlockSpec((1, Fp), lambda i: (0, 0), memory_space=pltpu.VMEM)

    diff, scaled, tu, ts = pl.pallas_call(
        functools.partial(_kernel, F),
        grid=grid,
        in_specs=[tile(), tile(), const(), const()],
        out_specs=[
            tile(),
            tile(),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Rp, Fp), jnp.float32),
            jax.ShapeDtypeStruct((Rp, Fp), jnp.float32),
            jax.ShapeDtypeStruct((Rp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Rp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(t, o, sh, sc)
    return (
        diff[:rows, :F],
        scaled[:rows, :F],
        tu[:rows, 0],
        ts[:rows, 0],
    )


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


_pallas_disabled = False  # sticky only when the kernel NEVER worked (compile)
_pallas_ever_worked = False
_transient_warned = False


def fused_anomaly_score(
    target: jnp.ndarray,
    output: jnp.ndarray,
    shift: jnp.ndarray,
    scale: jnp.ndarray,
    force: str = "auto",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """``(diff, scaled, total_unscaled, total_scaled)`` for a (rows, F)
    reconstruction — one fused pass on TPU, identical jnp math elsewhere.

    ``force``: "auto" (TPU -> kernel, else jnp), "pallas" (compiled
    kernel, errors propagate), "interpret" (kernel in interpreter mode,
    any backend), "jnp" (pure fallback). In "auto" mode a failure before
    the kernel has ever worked on this backend (a compile problem)
    disables it for the process; a failure after it has worked (e.g. a
    transient allocation error on one oversized request) falls back for
    that call only.
    """
    global _pallas_disabled, _pallas_ever_worked, _transient_warned
    if force == "jnp" or (
        force == "auto" and (_pallas_disabled or not _on_tpu())
    ):
        return _jnp_score(target, output, shift, scale)
    if force == "interpret":
        return _pallas_score(target, output, shift, scale, interpret=True)
    try:
        out = _pallas_score(target, output, shift, scale)
        # async dispatch: execution errors surface at result consumption,
        # which would be outside this try — block here so runtime failures
        # (e.g. allocation) are caught and can fall back per call
        jax.block_until_ready(out)
        _pallas_ever_worked = True
        return out
    except Exception:
        if force != "auto":
            raise
        if not _pallas_ever_worked:
            _pallas_disabled = True
            logger.warning(
                "Pallas scoring kernel failed to compile on backend %r; "
                "using XLA for the rest of this process",
                jax.default_backend(),
                exc_info=True,
            )
        elif not _transient_warned:
            _transient_warned = True
            logger.warning(
                "Pallas scoring kernel failed transiently; falling back to "
                "XLA for this call (further occurrences logged at DEBUG)",
                exc_info=True,
            )
        else:
            logger.debug("Pallas scoring kernel transient failure", exc_info=True)
        return _jnp_score(target, output, shift, scale)


# --------------------------------------------------------------------- #
# banked kernel: the whole coalesced batch in one grid
# --------------------------------------------------------------------- #

BANK_KERNEL_ENV = "GORDO_BANK_KERNEL"
_BANK_KERNEL_MODES = ("auto", "pallas", "interpret", "jnp")


# auto-mode probe result: None = not probed yet, True/False = the banked
# kernel compiled (or not) on this process's backend. An explicit
# GORDO_BANK_KERNEL=pallas bypasses the probe and propagates errors.
_banked_probe_ok = None


def _probe_banked_kernel() -> bool:
    """One tiny compile of the banked kernel, cached per process: auto
    mode must never bake a kernel that cannot compile into every bucket
    program (the banked analogue of ``fused_anomaly_score``'s
    compile-failure degrade — there the fallback is per call; here the
    mode is frozen into jit'd programs at build time, so the degrade has
    to happen BEFORE resolution)."""
    global _banked_probe_ok
    if _banked_probe_ok is None:
        try:
            out = _pallas_banked_score(
                jnp.zeros((1, 8, 4), jnp.float32),
                jnp.zeros((1, 8, 4), jnp.float32),
                jnp.zeros((1, 4), jnp.float32),
                jnp.ones((1, 4), jnp.float32),
                jnp.zeros((1,), jnp.int32),
            )
            jax.block_until_ready(out)
            _banked_probe_ok = True
        except Exception:
            _banked_probe_ok = False
            logger.warning(
                "Banked Pallas scoring kernel failed to compile on backend "
                "%r; banks built in auto mode use the XLA epilogue for the "
                "rest of this process (GORDO_BANK_KERNEL=pallas to surface "
                "the error)",
                jax.default_backend(),
                exc_info=True,
            )
    return _banked_probe_ok


def resolve_bank_kernel_mode(mode: str = None) -> str:
    """Concrete dispatch mode for the banked epilogue: ``mode`` (or env
    ``GORDO_BANK_KERNEL``, default ``auto``) resolved against the
    backend. Resolved ONCE per bank build — the choice is baked into the
    bucket's compiled program, not re-decided per request. ``auto`` on a
    TPU probe-compiles the kernel first and degrades to the XLA path if
    the probe fails; an explicit ``pallas`` never degrades."""
    raw = (mode or os.environ.get(BANK_KERNEL_ENV) or "auto").strip().lower()
    if raw not in _BANK_KERNEL_MODES:
        raise ValueError(
            f"{BANK_KERNEL_ENV} must be one of {'|'.join(_BANK_KERNEL_MODES)}, "
            f"got {raw!r}"
        )
    if raw == "auto":
        return "pallas" if _on_tpu() and _probe_banked_kernel() else "jnp"
    return raw


def _jnp_banked_score(target, output, shift_bank, scale_bank, idx):
    """Batched reference/XLA path: same math as per-member ``_jnp_score``
    with the scaler gather hoisted to one take. target/output: (B, T, F);
    shift/scale banks: (M, F); idx: (B,) member indices."""
    shift = shift_bank[idx][:, None, :]
    scale = scale_bank[idx][:, None, :]
    diff = jnp.abs(target - output)
    scaled = (diff - shift) * scale
    tot_u = jnp.sqrt(jnp.sum(diff * diff, axis=-1))
    tot_s = jnp.sqrt(jnp.sum(scaled * scaled, axis=-1))
    return diff, scaled, tot_u, tot_s


def _banked_kernel(n_features: int, idx_ref, t_ref, o_ref, shift_ref,
                   scale_ref, diff_ref, scaled_ref, tu_ref, ts_ref):
    # one (member, row-tile) grid step: refs are (1, row_tile, Fp) batch
    # tiles and (1, Fp) scaler rows already gathered by the scalar-
    # prefetched index map (idx_ref is consumed there, not here)
    t = t_ref[0]
    o = o_ref[0]
    diff = jnp.abs(t - o)
    # feature lanes beyond n_features are padding: zero them so the
    # scaled error's affine shift doesn't leak into the norms
    mask = jax.lax.broadcasted_iota(jnp.int32, diff.shape, 1) < n_features
    diff = jnp.where(mask, diff, 0.0)
    scaled = jnp.where(mask, (diff - shift_ref[0]) * scale_ref[0], 0.0)
    diff_ref[0] = diff
    scaled_ref[0] = scaled
    tu_ref[0] = jnp.sqrt(jnp.sum(diff * diff, axis=1, keepdims=True))
    ts_ref[0] = jnp.sqrt(jnp.sum(scaled * scaled, axis=1, keepdims=True))


def _pallas_banked_score(target, output, shift_bank, scale_bank, idx,
                         interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, F = target.shape
    Fp = -(-F // LANE) * LANE
    # adaptive row tile, exactly like the per-model kernel: short batch
    # rows tile at the next 8-sublane multiple, long ones at ROW_TILE
    row_tile = min(ROW_TILE, -(-T // 8) * 8)
    Rp = -(-T // row_tile) * row_tile
    pad3 = lambda a: jnp.pad(
        a.astype(jnp.float32), ((0, 0), (0, Rp - T), (0, Fp - F))
    )
    t = pad3(target)
    o = pad3(output)
    pad_bank = lambda a: jnp.pad(a.astype(jnp.float32), ((0, 0), (0, Fp - F)))
    sh, sc = pad_bank(shift_bank), pad_bank(scale_bank)

    # index maps receive (grid indices..., scalar-prefetch refs): the
    # scaler banks are gathered per batch slot by indexing the prefetched
    # member ids — the gather happens in the BlockSpec, so each grid step
    # DMAs exactly one member's scaler row into VMEM
    tile = lambda: pl.BlockSpec(
        (1, row_tile, Fp), lambda b, r, i: (b, r, 0), memory_space=pltpu.VMEM
    )
    gathered = lambda: pl.BlockSpec(
        (1, Fp), lambda b, r, i: (i[b], 0), memory_space=pltpu.VMEM
    )
    norm = lambda: pl.BlockSpec(
        (1, row_tile, 1), lambda b, r, i: (b, r, 0), memory_space=pltpu.VMEM
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, Rp // row_tile),
        in_specs=[tile(), tile(), gathered(), gathered()],
        out_specs=[tile(), tile(), norm(), norm()],
    )
    diff, scaled, tu, ts = pl.pallas_call(
        functools.partial(_banked_kernel, F),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, Rp, Fp), jnp.float32),
            jax.ShapeDtypeStruct((B, Rp, Fp), jnp.float32),
            jax.ShapeDtypeStruct((B, Rp, 1), jnp.float32),
            jax.ShapeDtypeStruct((B, Rp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(idx.astype(jnp.int32), t, o, sh, sc)
    return (
        diff[:, :T, :F],
        scaled[:, :T, :F],
        tu[:, :T, 0],
        ts[:, :T, 0],
    )


def banked_anomaly_score(
    target: jnp.ndarray,
    output: jnp.ndarray,
    shift_bank: jnp.ndarray,
    scale_bank: jnp.ndarray,
    idx: jnp.ndarray,
    mode: str = "jnp",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Banked scoring epilogue over a coalesced batch: ``(diff, scaled,
    total_unscaled, total_scaled)`` for (B, T, F) reconstructions against
    (M, F) stacked error scalers, member-selected by ``idx`` (B,).

    Traced inside the bank's per-bucket jit program; ``mode`` must
    already be resolved (:func:`resolve_bank_kernel_mode`): ``jnp`` is
    the XLA path (CPU fallback and parity reference), ``pallas`` the
    compiled TPU kernel, ``interpret`` the kernel in interpreter mode
    (how CI exercises the kernel logic without TPU hardware)."""
    if mode == "jnp":
        return _jnp_banked_score(target, output, shift_bank, scale_bank, idx)
    if mode == "pallas":
        return _pallas_banked_score(target, output, shift_bank, scale_bank, idx)
    if mode == "interpret":
        return _pallas_banked_score(
            target, output, shift_bank, scale_bank, idx, interpret=True
        )
    raise ValueError(
        f"banked_anomaly_score mode must be resolved to jnp|pallas|interpret, "
        f"got {mode!r}"
    )
