"""Fused anomaly-scoring epilogue as a Pallas TPU kernel.

The server's per-request hot loop (SURVEY.md §3.2; reference:
``DiffBasedAnomalyDetector.anomaly``) ends in an elementwise epilogue over
the reconstruction: ``|target - output|``, the per-feature error scaling,
and two row norms. As four separate XLA ops this reads the (rows, F)
operands from HBM several times and writes four results back; the Pallas
kernel streams each row tile through VMEM exactly once and emits all four
outputs from that single pass — one HBM read per operand, four writes,
zero intermediate round-trips.

Usage is transparent: :func:`fused_anomaly_score` dispatches to the kernel
on TPU backends and to an identical pure-jnp implementation elsewhere
(tests run it in interpreter mode via ``interpret=True`` to exercise the
kernel logic on CPU). Feature/row padding to hardware tiles (8 sublanes x
128 lanes for f32) happens in the wrapper; padded feature lanes are masked
inside the kernel so they contribute nothing to the scaled errors or the
norms.

Scope: the kernel accelerates the *per-model* scoring path
(``DiffBasedAnomalyDetector.anomaly`` — single model, one (rows, F)
request). The banked serving path (server/bank.py) runs the same epilogue
definition (``_jnp_score``) inside its vmapped per-bucket program, where
XLA fuses it into the batched matmul; moving that under the kernel (a
batched grid with per-model scaler gathers) is a possible follow-up once
profiled.
"""

import functools
import logging
from typing import Tuple

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

ROW_TILE = 256  # rows per grid step (multiple of the 8-sublane f32 tile)
LANE = 128


@jax.jit
def _jnp_score(target, output, shift, scale):
    """Reference implementation (also the non-TPU fallback)."""
    diff = jnp.abs(target - output)
    scaled = (diff - shift) * scale
    tot_u = jnp.sqrt(jnp.sum(diff * diff, axis=-1))
    tot_s = jnp.sqrt(jnp.sum(scaled * scaled, axis=-1))
    return diff, scaled, tot_u, tot_s


def _kernel(n_features: int, t_ref, o_ref, shift_ref, scale_ref,
            diff_ref, scaled_ref, tu_ref, ts_ref):
    t = t_ref[:]
    o = o_ref[:]
    diff = jnp.abs(t - o)
    # feature lanes beyond n_features are padding: zero them so the scaled
    # error's affine shift doesn't leak into the norms
    mask = jax.lax.broadcasted_iota(jnp.int32, diff.shape, 1) < n_features
    diff = jnp.where(mask, diff, 0.0)
    scaled = jnp.where(mask, (diff - shift_ref[:]) * scale_ref[:], 0.0)
    diff_ref[:] = diff
    scaled_ref[:] = scaled
    tu_ref[:] = jnp.sqrt(jnp.sum(diff * diff, axis=1, keepdims=True))
    ts_ref[:] = jnp.sqrt(jnp.sum(scaled * scaled, axis=1, keepdims=True))


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_score(target, output, shift, scale, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows, F = target.shape
    Fp = -(-F // LANE) * LANE
    # adaptive row tile: small requests shouldn't pad to a full ROW_TILE
    # (a 33-row request tiles at 40, not 256); multiples of the 8-sublane
    # f32 tile keep the hardware layout happy
    row_tile = min(ROW_TILE, -(-rows // 8) * 8)
    Rp = -(-rows // row_tile) * row_tile

    pad2 = lambda a: jnp.pad(a, ((0, Rp - rows), (0, Fp - F)))
    t = pad2(target.astype(jnp.float32))
    o = pad2(output.astype(jnp.float32))
    row_vec = lambda v: jnp.pad(v.astype(jnp.float32), (0, Fp - F))[None, :]
    sh, sc = row_vec(shift), row_vec(scale)

    grid = (Rp // row_tile,)
    tile = lambda: pl.BlockSpec(
        (row_tile, Fp), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    const = lambda: pl.BlockSpec((1, Fp), lambda i: (0, 0), memory_space=pltpu.VMEM)

    diff, scaled, tu, ts = pl.pallas_call(
        functools.partial(_kernel, F),
        grid=grid,
        in_specs=[tile(), tile(), const(), const()],
        out_specs=[
            tile(),
            tile(),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Rp, Fp), jnp.float32),
            jax.ShapeDtypeStruct((Rp, Fp), jnp.float32),
            jax.ShapeDtypeStruct((Rp, 1), jnp.float32),
            jax.ShapeDtypeStruct((Rp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(t, o, sh, sc)
    return (
        diff[:rows, :F],
        scaled[:rows, :F],
        tu[:rows, 0],
        ts[:rows, 0],
    )


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


_pallas_disabled = False  # sticky only when the kernel NEVER worked (compile)
_pallas_ever_worked = False
_transient_warned = False


def fused_anomaly_score(
    target: jnp.ndarray,
    output: jnp.ndarray,
    shift: jnp.ndarray,
    scale: jnp.ndarray,
    force: str = "auto",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """``(diff, scaled, total_unscaled, total_scaled)`` for a (rows, F)
    reconstruction — one fused pass on TPU, identical jnp math elsewhere.

    ``force``: "auto" (TPU -> kernel, else jnp), "pallas" (compiled
    kernel, errors propagate), "interpret" (kernel in interpreter mode,
    any backend), "jnp" (pure fallback). In "auto" mode a failure before
    the kernel has ever worked on this backend (a compile problem)
    disables it for the process; a failure after it has worked (e.g. a
    transient allocation error on one oversized request) falls back for
    that call only.
    """
    global _pallas_disabled, _pallas_ever_worked, _transient_warned
    if force == "jnp" or (
        force == "auto" and (_pallas_disabled or not _on_tpu())
    ):
        return _jnp_score(target, output, shift, scale)
    if force == "interpret":
        return _pallas_score(target, output, shift, scale, interpret=True)
    try:
        out = _pallas_score(target, output, shift, scale)
        # async dispatch: execution errors surface at result consumption,
        # which would be outside this try — block here so runtime failures
        # (e.g. allocation) are caught and can fall back per call
        jax.block_until_ready(out)
        _pallas_ever_worked = True
        return out
    except Exception:
        if force != "auto":
            raise
        if not _pallas_ever_worked:
            _pallas_disabled = True
            logger.warning(
                "Pallas scoring kernel failed to compile on backend %r; "
                "using XLA for the rest of this process",
                jax.default_backend(),
                exc_info=True,
            )
        elif not _transient_warned:
            _transient_warned = True
            logger.warning(
                "Pallas scoring kernel failed transiently; falling back to "
                "XLA for this call (further occurrences logged at DEBUG)",
                exc_info=True,
            )
        else:
            logger.debug("Pallas scoring kernel transient failure", exc_info=True)
        return _jnp_score(target, output, shift, scale)
