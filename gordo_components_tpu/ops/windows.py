"""Sliding-window construction for sequence models.

Reference parity: the reference feeds LSTMs via Keras ``TimeseriesGenerator``
with a ``lookback_window`` (gordo_components/model/models.py, unverified;
SURVEY.md §2 "model.models"). TPU-native inversion: windows are materialized
as a *batch* dimension with a gather — a static-shape op that XLA vectorizes
— rather than a Python generator, so the windowed batch feeds the MXU
directly and the whole train step stays inside one compiled program.
"""

import jax.numpy as jnp


def num_windows(n_samples: int, lookback: int) -> int:
    """Number of complete lookback windows in a series of ``n_samples``."""
    return max(0, n_samples - lookback + 1)


def sliding_windows(X: jnp.ndarray, lookback: int) -> jnp.ndarray:
    """(n_samples, n_features) -> (n_windows, lookback, n_features).

    Window ``i`` covers rows ``[i, i+lookback)``; static shapes throughout
    (``lookback`` must be a Python int at trace time).
    """
    n = X.shape[0]
    nw = num_windows(n, lookback)
    idx = jnp.arange(nw)[:, None] + jnp.arange(lookback)[None, :]  # (nw, lookback)
    return X[idx]
