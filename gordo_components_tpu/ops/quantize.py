"""Low-precision storage for the bank's stacked weights.

The HBM-resident :class:`~gordo_components_tpu.server.bank.ModelBank`
stacks every bucket's params into one pytree with a leading *member*
axis. At fleet scale those stacks bound models-per-chip: fp32 weights
are the single largest HBM tenant, and the scoring math never needs
them at full precision — compute happens in fp32 *after* a per-member
gather, so the stored stack only has to round-trip one member's worth
of weights per request (PAPERS.md #1: quantized serving is where TPU
stacks earn their margin).

Two storage modes below fp32 (``GORDO_BANK_DTYPE``):

- **bfloat16** — same exponent range as fp32, 8-bit mantissa: a plain
  ``astype`` halves the stack with a worst-case ~2^-9 relative rounding
  error per weight. No extra state.
- **int8** — per-member-per-tensor absmax scaling: each stacked leaf
  ``(M, ...)`` stores int8 codes plus an ``(M, 1, ...)`` fp32 scale
  (``absmax / 127`` over that member's tensor), ~4x smaller than fp32.
  One member's outlier cannot flatten another member's resolution
  because scales never cross the member axis.

Dequantization happens INSIDE the compiled scoring program, after the
per-member gather (:func:`dequantize_params`): HBM holds the small
representation, VMEM/compute sees fp32. The int8 container
(:class:`QuantizedLeaf`) is a registered pytree node so the bank's
existing machinery — ``device_put`` with a ``NamedSharding``,
``shard_map`` in-specs, ``jax.tree.map(lambda a: a[i], params)``
gathers — works on quantized stacks unchanged: both children carry the
leading member axis.
"""

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "BANK_DTYPES",
    "QuantizedLeaf",
    "dequantize_params",
    "normalize_bank_dtype",
    "quantize_stacked",
    "tree_weight_bytes",
]

# accepted GORDO_BANK_DTYPE values (aliases normalized below)
BANK_DTYPES = ("float32", "bfloat16", "int8")
_ALIASES = {
    "fp32": "float32", "f32": "float32",
    "bf16": "bfloat16",
    "i8": "int8",
}


def normalize_bank_dtype(value: str) -> str:
    """Canonical bank dtype from an env/config string (raises on junk —
    a typo'd fleet-wide knob must fail loudly at startup, not silently
    serve fp32)."""
    canon = _ALIASES.get(str(value).strip().lower(), str(value).strip().lower())
    if canon not in BANK_DTYPES:
        raise ValueError(
            f"bank dtype must be one of {'|'.join(BANK_DTYPES)}, got {value!r}"
        )
    return canon


@jax.tree_util.register_pytree_node_class
class QuantizedLeaf:
    """Int8 codes + broadcast-ready fp32 scale for one stacked tensor.

    ``values``: ``(M, ...)`` int8; ``scale``: ``(M, 1, ...)`` fp32 (same
    rank, so ``values * scale`` broadcasts after any prefix of leading
    axes is gathered away). Registered as a pytree node: tree maps, jit
    tracing, ``device_put`` sharding, and shard_map specs all descend
    into the two children transparently.
    """

    __slots__ = ("values", "scale")

    def __init__(self, values: Any, scale: Any):
        self.values = values
        self.scale = scale

    def tree_flatten(self) -> Tuple[Tuple[Any, Any], None]:
        return (self.values, self.scale), None

    @classmethod
    def tree_unflatten(cls, _aux, children) -> "QuantizedLeaf":
        return cls(*children)

    @property
    def nbytes(self) -> int:
        return int(self.values.nbytes) + int(self.scale.nbytes)

    def dequantize(self) -> jnp.ndarray:
        return self.values.astype(jnp.float32) * self.scale

    def __repr__(self) -> str:  # debugging aid, never on a hot path
        return (
            f"QuantizedLeaf(values={getattr(self.values, 'shape', None)}, "
            f"scale={getattr(self.scale, 'shape', None)})"
        )


def _quantize_leaf_int8(leaf: np.ndarray) -> QuantizedLeaf:
    """Per-member symmetric absmax quantization of one stacked leaf."""
    leaf = np.asarray(leaf, np.float32)
    axes = tuple(range(1, leaf.ndim))
    # rank-1 stacked scalars: (M,) -> each member's "tensor" is a scalar,
    # its own absmax
    absmax = np.max(np.abs(leaf), axis=axes, keepdims=True) if axes else np.abs(leaf)
    # an all-zero member tensor quantizes to zeros under ANY scale; 1.0
    # keeps the divide finite without perturbing the codes
    scale = np.where(absmax > 0.0, absmax / 127.0, 1.0).astype(np.float32)
    codes = np.clip(np.rint(leaf / scale), -127, 127).astype(np.int8)
    return QuantizedLeaf(codes, scale)


def _is_quantizable(leaf: Any) -> bool:
    """Only floating weight tensors shrink; integer/bool state (none in
    the current factories, but checkpoints may grow some) passes through
    untouched. jnp's dtype lattice, not numpy's: ml_dtypes extensions
    (bfloat16) are floating here but unknown to ``np.issubdtype``."""
    dtype = getattr(leaf, "dtype", None)
    return dtype is not None and jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def quantize_stacked(params: Any, bank_dtype: str) -> Any:
    """Quantize a stacked (leading member axis) params pytree for HBM
    residency. ``float32`` returns the tree unchanged (identity — the
    parity baseline must not even copy)."""
    bank_dtype = normalize_bank_dtype(bank_dtype)
    if bank_dtype == "float32":
        return params
    if bank_dtype == "bfloat16":
        return jax.tree.map(
            lambda a: np.asarray(a).astype(jnp.bfloat16)
            if _is_quantizable(a)
            else a,
            params,
        )
    return jax.tree.map(
        lambda a: _quantize_leaf_int8(a) if _is_quantizable(a) else a,
        params,
        is_leaf=lambda a: isinstance(a, QuantizedLeaf),
    )


def dequantize_params(params: Any) -> Any:
    """fp32 view of a (possibly gathered) quantized pytree — traced
    inside the compiled scoring program, so HBM holds the low-precision
    stack while all compute accumulates in fp32. Identity on fp32 leaves."""

    def _deq(leaf: Any):
        if isinstance(leaf, QuantizedLeaf):
            return leaf.dequantize()
        if _is_quantizable(leaf) and jnp.dtype(leaf.dtype) != jnp.float32:
            return leaf.astype(jnp.float32)
        return leaf

    return jax.tree.map(
        _deq, params, is_leaf=lambda a: isinstance(a, QuantizedLeaf)
    )


def tree_weight_bytes(params: Any) -> int:
    """Host/HBM footprint of a stacked params pytree in bytes
    (QuantizedLeaf children — codes and scales — both count: the scale
    overhead is exactly what keeps int8 below the naive 4x claim)."""
    return int(sum(leaf.nbytes for leaf in jax.tree.leaves(params)))
