"""Losses and scores for autoencoder training and evaluation.

Reference parity: the reference compiles Keras models with MSE-family losses
and scores estimators with ``sklearn.metrics.explained_variance_score``
(gordo_components/model/models.py, unverified; SURVEY.md §2). Implemented
here as pure jnp functions with an optional sample mask so padded rows
(fleet bucketing pads ragged per-machine datasets) drop out of the loss
without dynamic shapes.
"""

from typing import Optional

import jax.numpy as jnp


def mse_loss(pred: jnp.ndarray, target: jnp.ndarray, mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Mean squared error; ``mask`` is (n_samples,) with 1=real, 0=padding."""
    err = (pred - target) ** 2
    if mask is None:
        return jnp.mean(err)
    mask_b = mask.reshape((-1,) + (1,) * (err.ndim - 1))
    denom = jnp.maximum(jnp.sum(mask), 1.0) * (err.size / err.shape[0])
    return jnp.sum(err * mask_b) / denom


def _ratio_score(num: jnp.ndarray, den: jnp.ndarray) -> jnp.ndarray:
    """sklearn's 0/0 convention for variance-ratio scores: 1 - num/den,
    but a zero-variance output scores 1.0 when predicted perfectly
    (num == 0) and 0.0 otherwise."""
    safe = jnp.where(den > 0, den, 1.0)
    return jnp.where(
        den > 0, 1.0 - num / safe, jnp.where(num == 0, 1.0, 0.0)
    )


def explained_variance(y_true: jnp.ndarray, y_pred: jnp.ndarray) -> jnp.ndarray:
    """Uniform-average explained variance, matching
    ``sklearn.metrics.explained_variance_score`` defaults (including the
    0/0 -> 1.0 constant-column convention)."""
    diff = y_true - y_pred
    num = jnp.var(diff - jnp.mean(diff, axis=0), axis=0)
    den = jnp.var(y_true - jnp.mean(y_true, axis=0), axis=0)
    return jnp.mean(_ratio_score(num, den))


def regression_metrics(y_true: jnp.ndarray, y_pred: jnp.ndarray) -> dict:
    """The reference's evaluation metric set, uniform-averaged over
    outputs with sklearn-default semantics: explained variance, r2, MSE,
    MAE. One pass over (rows, features) arrays; returned as python
    floats for metadata."""
    diff = y_true - y_pred
    mse_per = jnp.mean(diff**2, axis=0)
    den = jnp.var(y_true - jnp.mean(y_true, axis=0), axis=0)
    return {
        "explained-variance": float(explained_variance(y_true, y_pred)),
        "r2-score": float(jnp.mean(_ratio_score(mse_per, den))),
        "mean-squared-error": float(jnp.mean(mse_per)),
        "mean-absolute-error": float(jnp.mean(jnp.abs(diff))),
    }
