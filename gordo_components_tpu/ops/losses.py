"""Losses and scores for autoencoder training and evaluation.

Reference parity: the reference compiles Keras models with MSE-family losses
and scores estimators with ``sklearn.metrics.explained_variance_score``
(gordo_components/model/models.py, unverified; SURVEY.md §2). Implemented
here as pure jnp functions with an optional sample mask so padded rows
(fleet bucketing pads ragged per-machine datasets) drop out of the loss
without dynamic shapes.
"""

from typing import Optional

import jax.numpy as jnp


def mse_loss(pred: jnp.ndarray, target: jnp.ndarray, mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Mean squared error; ``mask`` is (n_samples,) with 1=real, 0=padding."""
    err = (pred - target) ** 2
    if mask is None:
        return jnp.mean(err)
    mask_b = mask.reshape((-1,) + (1,) * (err.ndim - 1))
    denom = jnp.maximum(jnp.sum(mask), 1.0) * (err.size / err.shape[0])
    return jnp.sum(err * mask_b) / denom


def explained_variance(y_true: jnp.ndarray, y_pred: jnp.ndarray) -> jnp.ndarray:
    """Uniform-average explained variance, matching
    ``sklearn.metrics.explained_variance_score`` defaults."""
    diff = y_true - y_pred
    num = jnp.var(diff - jnp.mean(diff, axis=0), axis=0)
    den = jnp.var(y_true - jnp.mean(y_true, axis=0), axis=0)
    ev = jnp.where(den > 0, 1.0 - num / jnp.where(den > 0, den, 1.0), 0.0)
    return jnp.mean(ev)
