"""Feature scaling as pure functions over an explicit parameter struct.

Reference parity: the reference's default pipeline is
``sklearn.preprocessing.MinMaxScaler -> KerasAutoEncoder(kind=
"feedforward_hourglass")`` (SURVEY.md §2 "workflow", unverified). Here the
scaler is a pytree ``ScalerParams`` plus pure ``fit_*`` / ``scaler_transform``
functions so that scaling fuses into the jit'd train/score programs (one XLA
program end-to-end, no host round-trip) and vmaps over a model axis for the
fleet engine — 10k per-model scalers are just a stacked ScalerParams pytree.

All fits are NaN-tolerant (nan-min/max/mean) so upstream gap-filling can
leave NaNs for masked rows without poisoning scaler statistics.
"""

from typing import NamedTuple

import jax.numpy as jnp


class ScalerParams(NamedTuple):
    """Affine feature scaler: ``transform(x) = (x - shift) * scale``.

    Covers min-max ((x-min)/(max-min)), standard ((x-mean)/std), and
    identity as special cases, so a single struct serves every pipeline and
    stays homogeneous under ``vmap`` stacking.
    """

    shift: jnp.ndarray  # (n_features,)
    scale: jnp.ndarray  # (n_features,)


def fit_minmax(X: jnp.ndarray, feature_range=(0.0, 1.0), eps: float = 1e-12) -> ScalerParams:
    """Min-max scaler fit. X: (n_samples, n_features).

    Matches sklearn.MinMaxScaler semantics for the default (0,1) range;
    constant features map to the range minimum (scale guarded by ``eps``).
    """
    lo, hi = feature_range
    xmin = jnp.nanmin(X, axis=0)
    xmax = jnp.nanmax(X, axis=0)
    span = jnp.where(jnp.abs(xmax - xmin) < eps, 1.0, xmax - xmin)
    scale = (hi - lo) / span
    # transform = (x - xmin) * scale + lo  ==  (x - (xmin - lo/scale)) * scale
    shift = xmin - lo / scale
    return ScalerParams(shift=shift, scale=scale)


def fit_standard(X: jnp.ndarray, eps: float = 1e-12) -> ScalerParams:
    """Standard (z-score) scaler fit."""
    mean = jnp.nanmean(X, axis=0)
    std = jnp.sqrt(jnp.nanmean((X - mean) ** 2, axis=0))
    std = jnp.where(std < eps, 1.0, std)
    return ScalerParams(shift=mean, scale=1.0 / std)


def identity_scaler(n_features: int) -> ScalerParams:
    return ScalerParams(shift=jnp.zeros((n_features,)), scale=jnp.ones((n_features,)))


def scaler_transform(params: ScalerParams, X: jnp.ndarray) -> jnp.ndarray:
    return (X - params.shift) * params.scale


def scaler_inverse_transform(params: ScalerParams, X: jnp.ndarray) -> jnp.ndarray:
    return X / params.scale + params.shift
