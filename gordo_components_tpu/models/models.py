"""sklearn-compatible JAX/Flax autoencoder estimators.

Reference parity: gordo_components/model/models.py (unverified; SURVEY.md §2
"model.models") — ``KerasBaseEstimator`` / ``KerasAutoEncoder`` /
``KerasLSTMAutoEncoder`` / ``KerasLSTMForecast``. Same estimator semantics
(fit reconstructs X; LSTM variants window the series with
``lookback_window`` and reconstruct the current step or forecast t+1; score
is explained variance; per-epoch history recorded into metadata), but the
engine is the functional train core (train_core.py): one jit'd epoch
program, on-device shuffling, static shapes, bfloat16-capable.

These classes drop into ``sklearn.pipeline.Pipeline`` and pickle cleanly
(params are converted to numpy pytrees), which is what the serializer and
server rely on.
"""

import logging
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gordo_components_tpu.models.base import GordoBase
from gordo_components_tpu.models.register import lookup_factory
from gordo_components_tpu.models import factories  # noqa: F401 — registers factories
from gordo_components_tpu.models import train_core
from gordo_components_tpu.ops.losses import explained_variance, regression_metrics
from gordo_components_tpu.utils import capture_args

logger = logging.getLogger(__name__)


def _as_float32(X) -> np.ndarray:
    """DataFrame/array -> float32 ndarray (reference accepts both)."""
    if hasattr(X, "values"):
        X = X.values
    return np.asarray(X, dtype=np.float32)




class BaseEstimator(GordoBase):
    """Shared engine for all autoencoder estimators.

    ``kind`` selects a registered factory for this estimator's type (class
    name), exactly like the reference's ``KerasBaseEstimator``; remaining
    ``**kwargs`` flow to the factory.
    """

    # registry type; subclasses override (class name by default)
    @property
    def _registry_type(self) -> str:
        return type(self).__name__

    # DP shard_map's varying-manual-axes proof stays ON except for
    # recurrent modules, whose flax scan carries initialize unvarying and
    # trip the static analysis despite exact numerics (parallel/dp.py)
    _dp_check_vma = True

    @capture_args
    def __init__(
        self,
        kind: str = "feedforward_hourglass",
        batch_size: int = 100,
        epochs: int = 10,
        learning_rate: float = 1e-3,
        optimizer: str = "adam",
        loss: str = "auto",
        kl_weight: float = 1.0,
        validation_split: float = 0.0,
        early_stopping_patience: Optional[int] = None,
        early_stopping_min_delta: float = 0.0,
        seed: int = 0,
        compute_dtype: str = "float32",
        data_parallel: bool = False,
        **factory_kwargs,
    ):
        self.kind = kind
        self.batch_size = int(batch_size)
        self.epochs = int(epochs)
        self.learning_rate = float(learning_rate)
        self.optimizer = optimizer
        self.loss = loss
        self.kl_weight = float(kl_weight)
        self.validation_split = float(validation_split)
        self.early_stopping_patience = early_stopping_patience
        self.early_stopping_min_delta = float(early_stopping_min_delta)
        self.seed = int(seed)
        self.compute_dtype = compute_dtype
        # train with batch rows sharded over all devices (ICI DP) when more
        # than one device is visible; see fit() for the sharding design
        self.data_parallel = bool(data_parallel)
        self.factory_kwargs = factory_kwargs
        # fitted state
        self.params_ = None
        self.n_features_ = None
        self.history: Dict[str, list] = {}
        self._module = None
        # validate the kind eagerly for fail-fast config errors
        lookup_factory(self._registry_type, kind)

    # ------------------------------------------------------------------ #
    # module/data plumbing — subclasses specialize windowing semantics
    # ------------------------------------------------------------------ #

    def _build_module(self, n_features: int):
        factory = lookup_factory(self._registry_type, self.kind)
        return factory(
            n_features, compute_dtype=self.compute_dtype, **self.factory_kwargs
        )

    def _make_xy(self, X: np.ndarray, y: Optional[np.ndarray]):
        """(train_inputs, train_targets) — AE default: reconstruct X."""
        return X, X if y is None else _as_float32(y)

    def _resolved_loss(self) -> str:
        if self.loss != "auto":
            return self.loss
        # variational modules train with the ELBO; everything else MSE
        return "vae" if hasattr(self._module, "elbo_terms") else "mse"

    @property
    def module(self):
        if self._module is None:
            if self.n_features_ is None:
                raise RuntimeError("Model is not fitted; no module to build")
            self._module = self._build_module(self.n_features_)
        return self._module

    # ------------------------------------------------------------------ #
    # sklearn-style API
    # ------------------------------------------------------------------ #

    def fit(self, X, y=None, **kwargs):
        X = _as_float32(X)
        if X.ndim == 1:
            X = X[:, None]
        Xin, Yin = self._make_xy(X, y)
        self.n_features_ = int(X.shape[-1])
        self._module = None  # rebuild for (possibly) new n_features
        module = self.module

        n = Xin.shape[0]
        if n == 0:
            raise ValueError("Cannot fit on empty data")
        bs = min(self.batch_size, n)

        # host-side split, device-side everything else
        n_val = int(n * self.validation_split)
        if n_val > 0:
            Xtr, Ytr = Xin[:-n_val], Yin[:-n_val]
            Xva, Yva = Xin[-n_val:], Yin[-n_val:]
        else:
            Xtr, Ytr, Xva, Yva = Xin, Yin, None, None

        opt = train_core.make_optimizer(self.optimizer, self.learning_rate)
        loss = self._resolved_loss()
        init_fn, epoch_fn = train_core.make_train_fns(
            module, opt, bs, loss=loss, kl_weight=self.kl_weight
        )
        epoch_fn = jax.jit(epoch_fn, donate_argnums=(0,))

        # ---- data parallelism (BASELINE.json north star: DP over ICI) ----
        # Swap in the shard_map DP epoch: each batch's ROWS split across
        # the data mesh, gradients reconstructed with a count-weighted
        # psum (parallel/dp.py). Same shuffle, same rng stream -> same
        # model as the single-device fit; only the per-row gradient work
        # is partitioned. Runs on the largest device count dividing the
        # batch size so the split is exact.
        if self.data_parallel:
            from gordo_components_tpu.parallel.dp import (
                data_mesh,
                dp_device_count,
                make_dp_epoch_fn,
            )

            n_dp = dp_device_count(bs, len(jax.devices()))
            if n_dp > 1:
                dp_mesh = data_mesh(n_dp)
                epoch_fn = make_dp_epoch_fn(
                    module, opt, bs, dp_mesh, loss=loss,
                    kl_weight=self.kl_weight,
                    check_vma=self._dp_check_vma,
                )
                logger.info(
                    "Data-parallel fit: batch %d split over %d devices", bs, n_dp
                )
            else:
                logger.info(
                    "data_parallel requested but unusable (1 usable device "
                    "for batch_size=%d); single-device fit", bs,
                )

        Xp, Yp, mask, _ = train_core.pad_to_batches(Xtr, Ytr, bs)
        Xp, Yp, mask = jnp.asarray(Xp), jnp.asarray(Yp), jnp.asarray(mask)
        state = init_fn(jax.random.PRNGKey(self.seed), Xp[0])

        eval_fn = None
        if Xva is not None:
            eval_fn = jax.jit(
                train_core.make_eval_fn(module, bs, loss=loss, kl_weight=self.kl_weight)
            )
            Xvp, Yvp, vmask, _ = train_core.pad_to_batches(Xva, Yva, bs)
            Xvp, Yvp, vmask = jnp.asarray(Xvp), jnp.asarray(Yvp), jnp.asarray(vmask)

        self.history = {"loss": []}
        if eval_fn is not None:
            self.history["val_loss"] = []
        best, patience_left = np.inf, self.early_stopping_patience
        best_params = None
        for epoch in range(self.epochs):
            state, loss_val = epoch_fn(state, Xp, Yp, mask)
            loss_f = float(loss_val)
            self.history["loss"].append(loss_f)
            monitored = loss_f
            if eval_fn is not None:
                val = float(eval_fn(state, Xvp, Yvp, vmask))
                self.history["val_loss"].append(val)
                monitored = val
            if self.early_stopping_patience is not None:
                if monitored < best - self.early_stopping_min_delta:
                    best, patience_left = monitored, self.early_stopping_patience
                    best_params = jax.tree.map(np.asarray, state.params)
                else:
                    patience_left -= 1
                    if patience_left <= 0:
                        logger.info("Early stopping at epoch %d", epoch + 1)
                        break

        final = best_params if best_params is not None else state.params
        self.params_ = jax.tree.map(np.asarray, final)
        return self

    def _check_fitted(self):
        if self.params_ is None:
            raise RuntimeError(f"{type(self).__name__} has not been fitted")

    def predict(self, X) -> np.ndarray:
        """Reconstruction of X (reference: ``KerasAutoEncoder.transform``
        returns the autoencoder output)."""
        self._check_fitted()
        X = _as_float32(X)
        if X.ndim == 1:
            X = X[:, None]
        return train_core.batched_apply(self.module, self.params_, X)

    # sklearn Pipeline compatibility: AE estimators act as transformers too
    def transform(self, X) -> np.ndarray:
        return self.predict(X)

    def _scoring_pair(self, X, y):
        """(aligned target, prediction) — the single definition of scoring
        alignment, shared by ``score`` and ``score_metrics`` (sequence
        estimators override to drop the lookback warm-up rows)."""
        X = _as_float32(X)
        target = X if y is None else _as_float32(y)
        return target, self.predict(X)

    def score(self, X, y=None) -> float:
        """Explained variance of the reconstruction (reference semantics)."""
        self._check_fitted()
        target, pred = self._scoring_pair(X, y)
        return float(explained_variance(jnp.asarray(target), jnp.asarray(pred)))

    def score_metrics(self, X, y=None) -> Dict[str, float]:
        """The reference's full evaluation metric set (explained variance,
        r2, MSE, MAE) with ``score``'s exact target alignment — one
        prediction pass feeds all four."""
        self._check_fitted()
        target, pred = self._scoring_pair(X, y)
        return regression_metrics(jnp.asarray(target), jnp.asarray(pred))

    def get_metadata(self) -> Dict[str, Any]:
        md: Dict[str, Any] = {
            "type": type(self).__name__,
            "kind": self.kind,
            "params": _jsonable(self.get_params()),
        }
        if self.params_ is not None:
            md["n_features"] = self.n_features_
            md["history"] = self.history
            md["parameter_count"] = int(
                sum(int(np.size(p)) for p in jax.tree.leaves(self.params_))
            )
        return md

    # ------------------------------------------------------------------ #
    # pickling (serializer dump/load; reference made Keras picklable via
    # HDF5 bytes — here params are already a numpy pytree, so default
    # pickling works once the unpicklable Flax module is dropped)
    # ------------------------------------------------------------------ #

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_module"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)


class AutoEncoder(BaseEstimator):
    """Feedforward autoencoder over flat feature vectors
    (reference: ``KerasAutoEncoder``)."""


class SequenceBaseEstimator(BaseEstimator):
    """Shared windowing logic for sequence estimators: X is windowed into
    (n_windows, lookback_window, n_features) on device."""

    @capture_args
    def __init__(self, kind: str = "lstm_hourglass", lookback_window: int = 10, **kwargs):
        self.lookback_window = int(lookback_window)
        super().__init__(kind=kind, **kwargs)
        # capture_args on both ctors: merge so lookback_window is retained
        self._params = {"kind": kind, "lookback_window": lookback_window, **kwargs}

    # offset: prediction i corresponds to input row i + offset
    _target_offset = 0  # 0 => reconstruct window's last step

    def _window_inputs(self, X: np.ndarray) -> np.ndarray:
        lb = self.lookback_window
        if X.shape[0] < lb + self._target_offset:
            raise ValueError(
                f"Need at least lookback_window+{self._target_offset}="
                f"{lb + self._target_offset} rows, got {X.shape[0]}"
            )
        # host-side windowing: native multithreaded copy when available
        # (gordo_components_tpu/native); ops/windows.sliding_windows is the
        # in-graph equivalent used inside jit'd programs
        from gordo_components_tpu.native import sliding_windows_host

        W = sliding_windows_host(X, lb)
        if self._target_offset:
            W = W[: -self._target_offset]
        return W

    def _make_xy(self, X: np.ndarray, y=None):
        base = X if y is None else _as_float32(y)
        W = self._window_inputs(X)
        targets = base[self.lookback_window - 1 + self._target_offset :]
        return W, targets

    def predict(self, X) -> np.ndarray:
        """Output row i is the model value for input row
        ``i + lookback_window - 1 + offset`` (reference LSTM semantics:
        output is shorter than input by the warm-up window)."""
        self._check_fitted()
        X = _as_float32(X)
        if X.ndim == 1:
            X = X[:, None]
        W = self._window_inputs(X)
        return train_core.batched_apply(self.module, self.params_, W)

    def _scoring_pair(self, X, y):
        X = _as_float32(X)
        base = X if y is None else _as_float32(y)
        target = base[self.lookback_window - 1 + self._target_offset :]
        return target, self.predict(X)


class LSTMAutoEncoder(SequenceBaseEstimator):
    """Windowed sequence autoencoder reconstructing the current step
    (reference: ``KerasLSTMAutoEncoder``)."""

    _target_offset = 0
    _dp_check_vma = False  # recurrent: see BaseEstimator._dp_check_vma


class LSTMForecast(SequenceBaseEstimator):
    """Windowed sequence model forecasting t+1
    (reference: ``KerasLSTMForecast``)."""

    _target_offset = 1
    _dp_check_vma = False  # recurrent: see BaseEstimator._dp_check_vma


class ConvAutoEncoder(SequenceBaseEstimator):
    """Conv1D window autoencoder (extended zoo, BASELINE.json config 4).
    ``lookback_window`` must be divisible by ``2**len(channels)``."""

    @capture_args
    def __init__(self, kind: str = "conv1d_autoencoder", lookback_window: int = 16, **kwargs):
        # pin the conv implementation explicitly at build time: the
        # factory default changed once (lax -> matmul, 2026-07-31) and a
        # trained artifact must reload with the impl its thresholds were
        # calibrated under, not whatever the default is at load time
        kwargs.setdefault("conv_impl", "matmul")
        super().__init__(kind=kind, lookback_window=lookback_window, **kwargs)
        self._params = {"kind": kind, "lookback_window": lookback_window, **kwargs}

    def __setstate__(self, state):
        super().__setstate__(state)
        # artifacts pickled before the impl was pinned were built under
        # the then-default "lax"; resolve them to it so reload never
        # flips numerics under a trained model's thresholds. (Unpinned
        # pickles from the ~1h window where the default was already
        # matmul but the pin hadn't landed are indistinguishable and
        # resolve to lax too — a deliberate tie-break toward the years of
        # pre-flip artifacts; both impls agree within f32 1e-5 anyway.)
        self.factory_kwargs.setdefault("conv_impl", "lax")
        if hasattr(self, "_params"):
            self._params.setdefault("conv_impl", "lax")

    _target_offset = 0


def _jsonable(obj):
    """Best-effort conversion of captured params to JSON-safe values."""
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)
