"""Model-architecture registry.

Reference parity: ``register_model_builder`` in gordo_components/model/
register.py (unverified; SURVEY.md §2 "model.register") — maps estimator
class name -> {factory name -> callable}, enabling
``AutoEncoder(kind="feedforward_hourglass")``.

Factories here return **Flax modules** (pure apply functions) rather than
compiled Keras objects, so the same factory output feeds both the
single-model estimator and the vmap'd fleet engine.
"""

from typing import Callable, Dict

# estimator-class-name -> factory-name -> factory callable
FACTORY_REGISTRY: Dict[str, Dict[str, Callable]] = {}


def register_model_builder(type: str) -> Callable:
    """Class decorator-style registrar: ``@register_model_builder(type="AutoEncoder")``
    on a factory function registers it under that estimator type by its
    ``__name__``."""

    def decorator(factory: Callable) -> Callable:
        FACTORY_REGISTRY.setdefault(type, {})[factory.__name__] = factory
        return factory

    return decorator


def lookup_factory(type: str, kind: str) -> Callable:
    """Resolve a factory for an estimator type, with helpful errors."""
    # Reference-era estimator names map onto our JAX estimators so old
    # configs keep working (KerasAutoEncoder -> AutoEncoder, etc).
    aliases = {
        "KerasAutoEncoder": "AutoEncoder",
        "KerasLSTMAutoEncoder": "LSTMAutoEncoder",
        "KerasLSTMForecast": "LSTMForecast",
    }
    type = aliases.get(type, type)
    try:
        by_kind = FACTORY_REGISTRY[type]
    except KeyError:
        raise ValueError(
            f"No factories registered for estimator type {type!r}; known: {sorted(FACTORY_REGISTRY)}"
        )
    try:
        return by_kind[kind]
    except KeyError:
        raise ValueError(f"Unknown kind {kind!r} for {type!r}; known: {sorted(by_kind)}")
