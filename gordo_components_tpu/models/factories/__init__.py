"""Architecture factories returning Flax modules.

Reference parity: gordo_components/model/factories/ (unverified; SURVEY.md
§2 "model.factories") — ``feedforward_model`` / ``feedforward_symmetric`` /
``feedforward_hourglass`` and the ``lstm_*`` trio, plus the extended zoo
(Conv1D, variational) named in BASELINE.json config 4.

Importing this package registers every factory.
"""

from gordo_components_tpu.models.factories.feedforward import (
    feedforward_model,
    feedforward_symmetric,
    feedforward_hourglass,
    hourglass_calc_dims,
)
from gordo_components_tpu.models.factories.lstm import (
    lstm_model,
    lstm_symmetric,
    lstm_hourglass,
)
from gordo_components_tpu.models.factories.conv import conv1d_autoencoder
from gordo_components_tpu.models.factories.variational import feedforward_variational

__all__ = [
    "feedforward_model",
    "feedforward_symmetric",
    "feedforward_hourglass",
    "hourglass_calc_dims",
    "lstm_model",
    "lstm_symmetric",
    "lstm_hourglass",
    "conv1d_autoencoder",
    "feedforward_variational",
]
