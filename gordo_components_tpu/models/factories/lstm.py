"""LSTM autoencoder/forecast factories.

Reference parity: gordo_components/model/factories/lstm_autoencoder.py
(unverified; SURVEY.md §2) — stacked LSTM encoders over a
``lookback_window`` of timesteps, emitting one n_features vector (the
reconstruction of the current step for the autoencoder, or t+1 for the
forecaster; which target is the *estimator's* choice, not the factory's).

TPU-native design: recurrence is ``flax.linen.RNN`` (``lax.scan`` under the
hood — compiler-friendly sequential control flow, static window length);
windows are a batch dimension (ops/windows.py), so the per-step matmuls
batch onto the MXU across windows.
"""

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from gordo_components_tpu.models.factories.feedforward import resolve_activation
from gordo_components_tpu.models.factories.feedforward import hourglass_calc_dims
from gordo_components_tpu.models.register import register_model_builder


class LSTMStack(nn.Module):
    """Stacked LSTMs over (batch, lookback, n_features) -> (batch, n_features).

    Each layer's full output sequence feeds the next; the last layer's final
    hidden state passes through a Dense head back to feature space.
    """

    n_features: int
    dims: Tuple[int, ...]
    funcs: Tuple[str, ...]
    out_func: str = "linear"
    compute_dtype: str = "float32"

    @nn.compact
    def __call__(self, x):
        dtype = jnp.dtype(self.compute_dtype)
        x = x.astype(dtype)
        for dim, func in zip(self.dims, self.funcs):
            cell = nn.OptimizedLSTMCell(features=dim, dtype=dtype)
            x = nn.RNN(cell)(x)
            x = resolve_activation(func)(x)
        x = x[:, -1, :]  # final hidden state of last layer
        x = resolve_activation(self.out_func)(nn.Dense(self.n_features, dtype=dtype)(x))
        return x.astype(jnp.float32)


def _norm_funcs(funcs, n, default="tanh"):
    if funcs is None:
        return (default,) * n
    funcs = tuple(funcs)
    if len(funcs) != n:
        raise ValueError(f"Need {n} activation funcs, got {len(funcs)}")
    return funcs


@register_model_builder(type="LSTMAutoEncoder")
@register_model_builder(type="LSTMForecast")
def lstm_model(
    n_features: int,
    dims: Sequence[int] = (64, 64),
    funcs: Sequence[str] = None,
    out_func: str = "linear",
    compute_dtype: str = "float32",
    **_ignored,
) -> LSTMStack:
    """Fully specified LSTM stack (reference: ``lstm_model``)."""
    dims = tuple(dims)
    return LSTMStack(
        n_features=n_features,
        dims=dims,
        funcs=_norm_funcs(funcs, len(dims)),
        out_func=out_func,
        compute_dtype=compute_dtype,
    )


@register_model_builder(type="LSTMAutoEncoder")
@register_model_builder(type="LSTMForecast")
def lstm_symmetric(
    n_features: int,
    dims: Sequence[int] = (64, 32),
    funcs: Sequence[str] = None,
    out_func: str = "linear",
    compute_dtype: str = "float32",
    **_ignored,
) -> LSTMStack:
    """Symmetric LSTM autoencoder: encoder dims then mirrored decoder dims
    (reference: ``lstm_symmetric``)."""
    dims = tuple(dims)
    if not dims:
        raise ValueError("dims must be non-empty")
    funcs = _norm_funcs(funcs, len(dims))
    full_dims = dims + tuple(reversed(dims))
    full_funcs = funcs + tuple(reversed(funcs))
    return lstm_model(
        n_features, dims=full_dims, funcs=full_funcs, out_func=out_func,
        compute_dtype=compute_dtype,
    )


@register_model_builder(type="LSTMAutoEncoder")
@register_model_builder(type="LSTMForecast")
def lstm_hourglass(
    n_features: int,
    encoding_layers: int = 3,
    compression_factor: float = 0.5,
    func: str = "tanh",
    out_func: str = "linear",
    compute_dtype: str = "float32",
    **_ignored,
) -> LSTMStack:
    """Hourglass LSTM (reference: ``lstm_hourglass``): layer sizes shrink by
    ``compression_factor`` then mirror back up."""
    dims = hourglass_calc_dims(compression_factor, encoding_layers, n_features)
    return lstm_symmetric(
        n_features, dims=dims, funcs=(func,) * len(dims), out_func=out_func,
        compute_dtype=compute_dtype,
    )
