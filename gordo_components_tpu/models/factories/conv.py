"""Conv1D autoencoder factory — extended model zoo (BASELINE.json config 4;
not present upstream, SURVEY.md §7 stage 7).

Operates on lookback windows (batch, lookback, n_features): a strided
Conv1D encoder halves the time axis per layer, a ConvTranspose decoder
mirrors it, and the estimator takes the *last* reconstructed step as the
model output so Conv models drop into the same window-batch training loop
as the LSTMs. Convolutions lower to MXU matmuls on TPU.
"""

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from gordo_components_tpu.models.factories.feedforward import resolve_activation
from gordo_components_tpu.models.register import register_model_builder


class Conv1DAutoEncoder(nn.Module):
    n_features: int
    channels: Tuple[int, ...]
    kernel_size: int
    func: str
    compute_dtype: str = "float32"

    @nn.compact
    def __call__(self, x):
        # x: (batch, lookback, n_features); lookback must be divisible by
        # 2**len(channels) (the estimator pads windows to this).
        dtype = jnp.dtype(self.compute_dtype)
        x = x.astype(dtype)
        act = resolve_activation(self.func)
        for ch in self.channels:
            x = act(nn.Conv(ch, (self.kernel_size,), strides=(2,), dtype=dtype)(x))
        for ch in reversed(self.channels):
            x = act(nn.ConvTranspose(ch, (self.kernel_size,), strides=(2,), dtype=dtype)(x))
        x = nn.Conv(self.n_features, (self.kernel_size,), dtype=dtype)(x)
        return x[:, -1, :].astype(jnp.float32)


@register_model_builder(type="ConvAutoEncoder")
@register_model_builder(type="LSTMAutoEncoder")
def conv1d_autoencoder(
    n_features: int,
    channels: Sequence[int] = (32, 16),
    kernel_size: int = 3,
    func: str = "relu",
    compute_dtype: str = "float32",
    **_ignored,
) -> Conv1DAutoEncoder:
    return Conv1DAutoEncoder(
        n_features=n_features,
        channels=tuple(channels),
        kernel_size=kernel_size,
        func=func,
        compute_dtype=compute_dtype,
    )
