"""Conv1D autoencoder factory — extended model zoo (BASELINE.json config 4;
not present upstream, SURVEY.md §7 stage 7).

Operates on lookback windows (batch, lookback, n_features): a strided
Conv1D encoder halves the time axis per layer, a ConvTranspose decoder
mirrors it, and the estimator takes the *last* reconstructed step as the
model output so Conv models drop into the same window-batch training loop
as the LSTMs.

``conv_impl="matmul"`` (the DEFAULT) lowers every (transpose)
convolution to K strided SLICES + MATMULS instead of an XLA conv op:
numerically the same convolution with the same flax parameter tree, so
the two paths are interchangeable on any artifact/checkpoint. Slices,
not an im2col gather — a slice transposes to zero-padding while a
gather transposes to a scatter-add that erases the forward win in the
backward pass. Matmul is the default on clean-core CPU measurements
(2026-07-31): vmapped gangs 3.1-15.9x faster (the gap GROWS with
channel width — XLA's grouped-conv lowering of vmapped convs is the
conv fleet's below-parity culprit, VERDICT r3 weak #1), single builds
4.7-8.2x, across bf16/f32 and channels (16,8)..(64,32). It is also the
MXU-native formulation: the systolic array runs matmuls, and
tiny-channel convs tile poorly. ``conv_impl="lax"`` keeps the stock
ops; bench.py A/Bs both on whatever backend it runs
(``conv_matmul_impl_vs_lax``).
"""

import os
from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from gordo_components_tpu.models.factories.feedforward import resolve_activation
from gordo_components_tpu.models.register import register_model_builder

# flips the conv1d fleet's DEFAULT implementation ("matmul" | "lax");
# an explicit conv_impl kwarg (or a pickled estimator's pinned value)
# always takes precedence
CONV_IMPL_ENV = "GORDO_CONV_IMPL"


class MatmulConv(nn.Module):
    """SAME-padding strided Conv1D as K strided slices + matmuls —
    ``y[:, o] = sum_k xpad[:, o*s + k] @ kernel[k]`` — with parameter
    names and shapes identical to ``nn.Conv`` (kernel (K, F, C), bias
    (C,)). Slices (not gathers) keep the BACKWARD cheap: a slice
    transposes to zero-padding, while an im2col gather transposes to a
    scatter-add that erases the forward win on CPU (measured)."""

    features: int
    kernel_size: int
    stride: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        K, F, s = self.kernel_size, x.shape[-1], self.stride
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (K, F, self.features)
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        L = x.shape[1]
        out_len = -(-L // s)
        pad_total = max((out_len - 1) * s + K - L, 0)
        lo = pad_total // 2
        xp = jnp.pad(x, ((0, 0), (lo, pad_total - lo), (0, 0)))
        kc = kernel.astype(self.dtype)
        y = bias.astype(self.dtype)
        for k in range(K):
            y = y + xp[:, k : k + (out_len - 1) * s + 1 : s, :] @ kc[k]
        return y


class MatmulConvTranspose(nn.Module):
    """SAME-padding strided ConvTranspose1D as dilate + K slices +
    matmuls; parameter tree identical to ``nn.ConvTranspose``. Padding
    split is CEIL-major — calibrated exactly against flax (K=2..6,
    stride 2)."""

    features: int
    kernel_size: int
    stride: int = 2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        K, F, s = self.kernel_size, x.shape[-1], self.stride
        if s != 2:
            # the ceil-major padding split below is verified against
            # flax's _conv_transpose_padding for stride 2 only; other
            # strides distribute padding differently and would silently
            # shift outputs — extend the calibration before allowing them
            raise NotImplementedError(
                "MatmulConvTranspose parity is calibrated for stride 2"
            )
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (K, F, self.features)
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        B, L = x.shape[0], x.shape[1]
        # conv_transpose == conv with the input dilated by the stride
        dil_len = L * s - (s - 1)
        dil = jnp.zeros((B, dil_len, F), x.dtype).at[:, ::s, :].set(x)
        out_len = L * s
        pad_total = out_len - dil_len + K - 1
        lo = pad_total - pad_total // 2
        xp = jnp.pad(dil, ((0, 0), (lo, pad_total - lo), (0, 0)))
        kc = kernel.astype(self.dtype)
        y = bias.astype(self.dtype)
        for k in range(K):
            y = y + xp[:, k : k + out_len, :] @ kc[k]
        return y


class Conv1DAutoEncoder(nn.Module):
    n_features: int
    channels: Tuple[int, ...]
    kernel_size: int
    func: str
    compute_dtype: str = "float32"
    conv_impl: str = "matmul"  # "matmul" (slice+matmul) | "lax" (stock ops)

    @nn.compact
    def __call__(self, x):
        # x: (batch, lookback, n_features); lookback must be divisible by
        # 2**len(channels) (the estimator pads windows to this).
        dtype = jnp.dtype(self.compute_dtype)
        x = x.astype(dtype)
        act = resolve_activation(self.func)
        if self.conv_impl not in ("lax", "matmul"):
            # a typo'd value must not silently pick a non-default perf
            # profile (numerics are identical, so it would go unnoticed)
            raise ValueError(
                f"conv_impl must be 'lax' or 'matmul', got {self.conv_impl!r}"
            )
        matmul = self.conv_impl == "matmul"
        # explicit names preserve the stock flax auto-naming (Conv_0,
        # ConvTranspose_0, ...) so both impls share one parameter tree and
        # existing artifacts/checkpoints load into either
        ci = ti = 0
        for ch in self.channels:
            layer = (
                MatmulConv(ch, self.kernel_size, 2, dtype, name=f"Conv_{ci}")
                if matmul
                else nn.Conv(
                    ch, (self.kernel_size,), strides=(2,), dtype=dtype,
                    name=f"Conv_{ci}",
                )
            )
            x = act(layer(x))
            ci += 1
        for ch in reversed(self.channels):
            layer = (
                MatmulConvTranspose(
                    ch, self.kernel_size, 2, dtype, name=f"ConvTranspose_{ti}"
                )
                if matmul
                else nn.ConvTranspose(
                    ch, (self.kernel_size,), strides=(2,), dtype=dtype,
                    name=f"ConvTranspose_{ti}",
                )
            )
            x = act(layer(x))
            ti += 1
        final = (
            MatmulConv(self.n_features, self.kernel_size, 1, dtype, name=f"Conv_{ci}")
            if matmul
            else nn.Conv(
                self.n_features, (self.kernel_size,), dtype=dtype,
                name=f"Conv_{ci}",
            )
        )
        x = final(x)
        return x[:, -1, :].astype(jnp.float32)


@register_model_builder(type="ConvAutoEncoder")
@register_model_builder(type="LSTMAutoEncoder")
def conv1d_autoencoder(
    n_features: int,
    channels: Sequence[int] = (32, 16),
    kernel_size: int = 3,
    func: str = "relu",
    compute_dtype: str = "float32",
    conv_impl: Optional[str] = None,
    **_ignored,
) -> Conv1DAutoEncoder:
    # default impl: the matmul formulation the bench measures at 3.55x
    # (``conv_matmul_impl_vs_lax``). ``GORDO_CONV_IMPL=lax`` flips the
    # DEFAULT back to the stock lax ops (escape hatch; parity pinned by
    # tests/test_conv_impl.py) — an explicit ``conv_impl`` kwarg always
    # wins, and a pickled estimator pins whichever impl built it.
    if conv_impl is None:
        conv_impl = os.environ.get(CONV_IMPL_ENV, "").strip().lower() or "matmul"
    return Conv1DAutoEncoder(
        n_features=n_features,
        channels=tuple(channels),
        kernel_size=kernel_size,
        func=func,
        compute_dtype=compute_dtype,
        conv_impl=conv_impl,
    )
