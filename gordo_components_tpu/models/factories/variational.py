"""Variational feedforward autoencoder — extended model zoo (BASELINE.json
config 4; not present upstream, SURVEY.md §7 stage 7).

Standard VAE over flat feature vectors: dense encoder to (mu, logvar),
reparameterized sample, dense decoder. The module's ``__call__`` returns the
mean-decoded reconstruction (deterministic, for scoring); training uses
``elbo_terms`` via the estimator's loss hook, which adds the KL term. The
sampling rng is a Flax ``'sample'`` rng collection so the fleet engine can
vmap per-model rngs.
"""

from typing import Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from gordo_components_tpu.models.factories.feedforward import resolve_activation
from gordo_components_tpu.models.register import register_model_builder


class VariationalAutoEncoder(nn.Module):
    n_features: int
    dims: Tuple[int, ...]
    latent_dim: int
    func: str
    compute_dtype: str = "float32"

    def _encode(self, x):
        dtype = jnp.dtype(self.compute_dtype)
        act = resolve_activation(self.func)
        h = x.astype(dtype)
        for i, dim in enumerate(self.dims):
            h = act(nn.Dense(dim, dtype=dtype, name=f"enc_{i}")(h))
        mu = nn.Dense(self.latent_dim, dtype=dtype, name="mu")(h)
        logvar = nn.Dense(self.latent_dim, dtype=dtype, name="logvar")(h)
        return mu, logvar

    def _decode(self, z):
        dtype = jnp.dtype(self.compute_dtype)
        act = resolve_activation(self.func)
        h = z
        for i, dim in enumerate(reversed(self.dims)):
            h = act(nn.Dense(dim, dtype=dtype, name=f"dec_{i}")(h))
        return nn.Dense(self.n_features, dtype=dtype, name="out")(h).astype(jnp.float32)

    @nn.compact
    def __call__(self, x):
        mu, logvar = self._encode(x)
        return self._decode(mu)  # deterministic reconstruction for scoring

    @nn.compact
    def elbo_terms(self, x):
        """Returns (reconstruction, kl_per_sample) using a sampled latent."""
        mu, logvar = self._encode(x)
        rng = self.make_rng("sample")
        noise = jax.random.normal(rng, mu.shape, dtype=mu.dtype)
        z = mu + jnp.exp(0.5 * logvar) * noise
        recon = self._decode(z)
        kl = -0.5 * jnp.sum(1 + logvar - mu**2 - jnp.exp(logvar), axis=-1)
        return recon, kl.astype(jnp.float32)


@register_model_builder(type="AutoEncoder")
def feedforward_variational(
    n_features: int,
    dims: Sequence[int] = (128, 64),
    latent_dim: int = 16,
    func: str = "tanh",
    compute_dtype: str = "float32",
    **_ignored,
) -> VariationalAutoEncoder:
    return VariationalAutoEncoder(
        n_features=n_features,
        dims=tuple(dims),
        latent_dim=latent_dim,
        func=func,
        compute_dtype=compute_dtype,
    )
