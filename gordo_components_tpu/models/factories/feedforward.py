"""Feedforward autoencoder factories.

Reference parity: gordo_components/model/factories/feedforward_autoencoder.py
(unverified; SURVEY.md §2) — dense encoder/decoder stacks where
``feedforward_hourglass`` shrinks encoder dims by ``compression_factor``
over ``encoding_layers``. TPU notes: all layers are plain matmuls (MXU
work); the module computes in a configurable dtype (bfloat16 by default for
the fleet path) while params stay float32.
"""

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from gordo_components_tpu.models.register import register_model_builder

_ACTIVATIONS = {
    "tanh": nn.tanh,
    "relu": nn.relu,
    "sigmoid": nn.sigmoid,
    "elu": nn.elu,
    "linear": lambda x: x,
    "softplus": nn.softplus,
}


def resolve_activation(name: str):
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(f"Unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}")


class FeedForwardAutoEncoder(nn.Module):
    """Dense autoencoder: encoder dims, then decoder dims, then a linear
    output layer back to ``n_features``."""

    n_features: int
    encoding_dim: Tuple[int, ...]
    decoding_dim: Tuple[int, ...]
    encoding_func: Tuple[str, ...]
    decoding_func: Tuple[str, ...]
    out_func: str = "linear"
    compute_dtype: str = "float32"

    @nn.compact
    def __call__(self, x):
        dtype = jnp.dtype(self.compute_dtype)
        x = x.astype(dtype)
        for dim, func in zip(self.encoding_dim, self.encoding_func):
            x = resolve_activation(func)(nn.Dense(dim, dtype=dtype)(x))
        for dim, func in zip(self.decoding_dim, self.decoding_func):
            x = resolve_activation(func)(nn.Dense(dim, dtype=dtype)(x))
        x = resolve_activation(self.out_func)(nn.Dense(self.n_features, dtype=dtype)(x))
        return x.astype(jnp.float32)


def _norm_funcs(funcs, n, default):
    if funcs is None:
        return (default,) * n
    funcs = tuple(funcs)
    if len(funcs) != n:
        raise ValueError(f"Need {n} activation funcs, got {len(funcs)}")
    return funcs


@register_model_builder(type="AutoEncoder")
def feedforward_model(
    n_features: int,
    encoding_dim: Sequence[int] = (256, 128, 64),
    decoding_dim: Sequence[int] = (64, 128, 256),
    encoding_func: Sequence[str] = None,
    decoding_func: Sequence[str] = None,
    out_func: str = "linear",
    compute_dtype: str = "float32",
    **_ignored,
) -> FeedForwardAutoEncoder:
    """Fully specified dense autoencoder (reference: ``feedforward_model``)."""
    return FeedForwardAutoEncoder(
        n_features=n_features,
        encoding_dim=tuple(encoding_dim),
        decoding_dim=tuple(decoding_dim),
        encoding_func=_norm_funcs(encoding_func, len(encoding_dim), "tanh"),
        decoding_func=_norm_funcs(decoding_func, len(decoding_dim), "tanh"),
        out_func=out_func,
        compute_dtype=compute_dtype,
    )


@register_model_builder(type="AutoEncoder")
def feedforward_symmetric(
    n_features: int,
    dims: Sequence[int] = (256, 128, 64),
    funcs: Sequence[str] = None,
    compute_dtype: str = "float32",
    **_ignored,
) -> FeedForwardAutoEncoder:
    """Symmetric dense autoencoder: decoder mirrors the encoder
    (reference: ``feedforward_symmetric``)."""
    if not dims:
        raise ValueError("dims must be non-empty")
    funcs = _norm_funcs(funcs, len(dims), "tanh")
    return feedforward_model(
        n_features,
        encoding_dim=tuple(dims),
        decoding_dim=tuple(reversed(dims)),
        encoding_func=funcs,
        decoding_func=tuple(reversed(funcs)),
        compute_dtype=compute_dtype,
    )


def hourglass_calc_dims(compression_factor: float, encoding_layers: int, n_features: int):
    """Linearly interpolated layer dims from ``n_features`` down to
    ``n_features * compression_factor`` (reference hourglass geometry)."""
    if not 0 <= compression_factor <= 1:
        raise ValueError("compression_factor must be 0..1")
    if encoding_layers < 1:
        raise ValueError("encoding_layers must be >= 1")
    smallest = max(1, round(n_features * compression_factor))
    dims = [
        max(1, round(n_features - (n_features - smallest) * (i / encoding_layers)))
        for i in range(1, encoding_layers + 1)
    ]
    return tuple(dims)


@register_model_builder(type="AutoEncoder")
def feedforward_hourglass(
    n_features: int,
    encoding_layers: int = 3,
    compression_factor: float = 0.5,
    func: str = "tanh",
    compute_dtype: str = "float32",
    **_ignored,
) -> FeedForwardAutoEncoder:
    """Hourglass dense autoencoder — the reference's default model
    (reference: ``feedforward_hourglass``)."""
    dims = hourglass_calc_dims(compression_factor, encoding_layers, n_features)
    return feedforward_symmetric(
        n_features, dims=dims, funcs=(func,) * len(dims), compute_dtype=compute_dtype
    )
