"""Reconstruction-error anomaly detector.

Reference parity: ``DiffBasedAnomalyDetector`` in
gordo_components/model/anomaly/diff.py (unverified; SURVEY.md §2
"model.anomaly" — named explicitly in BASELINE.json): wraps a base
pipeline/estimator; fit learns a per-feature scaling of the reconstruction
error; ``anomaly(X)`` returns a multi-level DataFrame with model-input,
model-output, per-tag anomaly (scaled + unscaled), and total-anomaly
columns; cross-validated thresholds land in metadata.

TPU-native notes: the scoring math (diff, per-feature error scaling, norms)
is a single jit'd program (``_score_fn``) over float32 device arrays — this
is the server's per-request hot loop (SURVEY.md §3.2) — with the pandas
frame assembled host-side only at the edge.
"""

import logging
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from gordo_components_tpu.models.anomaly.base import AnomalyDetectorBase
from gordo_components_tpu.models.base import (
    GordoBase,
    score_metrics_of,
    transform_through_steps,
)
from gordo_components_tpu.ops.scaler import (
    ScalerParams,
    fit_minmax,
    scaler_transform,
)
from gordo_components_tpu.utils import capture_args

logger = logging.getLogger(__name__)


def _score_fn(err_scale: ScalerParams, target: jnp.ndarray, output: jnp.ndarray):
    """diff -> (abs diff, scaled abs diff, total norms). One program:
    a fused Pallas pass on TPU, the same math via jit'd XLA elsewhere
    (ops/pallas_score.py — dispatch happens outside jit so a kernel
    compile failure can fall back cleanly)."""
    from gordo_components_tpu.ops.pallas_score import fused_anomaly_score

    return fused_anomaly_score(target, output, err_scale.shift, err_scale.scale)


def assemble_anomaly_frame(
    tags, inp, output, diff, scaled, tot_u, tot_s, index=None
) -> pd.DataFrame:
    """Assemble the reference's multi-level anomaly frame from score arrays.

    Shared by :meth:`DiffBasedAnomalyDetector.anomaly` and the server's
    HBM-resident model bank (server/bank.py) so the two scoring paths are
    frame-identical by construction.
    """
    inp = np.asarray(inp)
    frames = {("model-input", t): inp[:, i] for i, t in enumerate(tags)}
    frames.update(
        {("model-output", t): np.asarray(output)[:, i] for i, t in enumerate(tags)}
    )
    frames.update(
        {("tag-anomaly-unscaled", t): np.asarray(diff)[:, i] for i, t in enumerate(tags)}
    )
    frames.update(
        {("tag-anomaly-scaled", t): np.asarray(scaled)[:, i] for i, t in enumerate(tags)}
    )
    df = pd.DataFrame(frames, index=index)
    df[("total-anomaly-unscaled", "")] = np.asarray(tot_u)
    df[("total-anomaly-scaled", "")] = np.asarray(tot_s)
    df.columns = pd.MultiIndex.from_tuples(df.columns)
    return df


class DiffBasedAnomalyDetector(AnomalyDetectorBase):
    """Anomaly = norm of (per-feature scaled) |y - reconstruction|."""

    @capture_args
    def __init__(
        self,
        base_estimator: Optional[GordoBase] = None,
        require_thresholds: bool = False,
        threshold_quantile: float = 1.0,
    ):
        # default mirrors the reference's default model: hourglass AE
        if base_estimator is None:
            from gordo_components_tpu.models.models import AutoEncoder

            base_estimator = AutoEncoder(kind="feedforward_hourglass")
        self.base_estimator = base_estimator
        self.require_thresholds = require_thresholds
        self.threshold_quantile = float(threshold_quantile)
        self.error_scaler_: Optional[ScalerParams] = None
        self.feature_thresholds_: Optional[np.ndarray] = None
        self.total_threshold_: Optional[float] = None
        # how the thresholds were computed: "exact" (np.quantile over
        # materialized errors — this class's own fit) or "histogram-8192"
        # (the fleet's streaming pass for sequence members with q < 1,
        # error bounded by range/8192; parallel/fleet.py). Recorded in
        # metadata so an operator comparing fleet- and single-built
        # thresholds knows why they differ at the 4th decimal.
        self.threshold_method_: Optional[str] = None
        self.tags_: Optional[list] = None

    # ------------------------------------------------------------------ #

    @property
    def _offset(self) -> int:
        """Rows consumed by sequence warm-up: output row i corresponds to
        input row i + offset (0 for feedforward)."""
        est = self._final_estimator
        return getattr(est, "lookback_window", 1) - 1 + getattr(est, "_target_offset", 0)

    @property
    def _final_estimator(self):
        est = self.base_estimator
        if hasattr(est, "steps"):  # sklearn Pipeline
            return est.steps[-1][1]
        return est

    def _model_space(self, X: np.ndarray) -> np.ndarray:
        """Map raw values through the pipeline's pre-model transformers so the
        diff is computed in the same space the model reconstructs."""
        est = self.base_estimator
        if hasattr(est, "steps"):
            for _, step in est.steps[:-1]:
                X = step.transform(X)
        return np.asarray(X, dtype=np.float32)

    def _predict_model_space(self, X: np.ndarray) -> np.ndarray:
        est = self.base_estimator
        if hasattr(est, "steps"):
            X = transform_through_steps(est, X)
            return np.asarray(est.steps[-1][1].predict(X), dtype=np.float32)
        return np.asarray(est.predict(X), dtype=np.float32)

    # ------------------------------------------------------------------ #

    def fit(self, X, y=None, **kwargs):
        if isinstance(X, pd.DataFrame):
            self.tags_ = [str(c) for c in X.columns]
            Xv = X.values.astype(np.float32)
        else:
            Xv = np.asarray(X, dtype=np.float32)
            self.tags_ = [f"feature-{i}" for i in range(Xv.shape[-1])]

        self.base_estimator.fit(Xv, y)

        # per-feature error scaling learned from the training residuals
        output = self._predict_model_space(Xv)
        target = self._model_space(Xv if y is None else np.asarray(y, np.float32))
        target = target[self._offset :][: output.shape[0]]
        diff = np.abs(target - output)
        self.error_scaler_ = jax.tree.map(np.asarray, fit_minmax(jnp.asarray(diff)))

        # thresholds: quantile of training scaled errors (the builder's
        # cross-validation path refines these across folds)
        scaled = np.asarray(
            scaler_transform(ScalerParams(*self.error_scaler_), jnp.asarray(diff))
        )
        q = self.threshold_quantile
        self.feature_thresholds_ = np.quantile(scaled, q, axis=0)
        self.total_threshold_ = float(
            np.quantile(np.linalg.norm(scaled, axis=-1), q)
        )
        self.threshold_method_ = "exact"
        return self

    def predict(self, X):
        return self.base_estimator.predict(X)

    def score(self, X, y=None) -> float:
        return self.base_estimator.score(X, y)

    def score_metrics(self, X, y=None):
        return score_metrics_of(self.base_estimator, X, y)

    def _check_fitted(self):
        if self.error_scaler_ is None:
            raise RuntimeError("DiffBasedAnomalyDetector has not been fitted")
        if self.require_thresholds and self.total_threshold_ is None:
            raise RuntimeError("Thresholds required but not computed")

    def anomaly(self, X, y=None) -> pd.DataFrame:
        """Multi-level anomaly frame (reference column scheme):
        ``model-input``, ``model-output``, ``tag-anomaly-unscaled``,
        ``tag-anomaly-scaled``, ``total-anomaly-unscaled``,
        ``total-anomaly-scaled``."""
        self._check_fitted()
        index = X.index[self._offset :] if isinstance(X, pd.DataFrame) else None
        Xv = X.values.astype(np.float32) if isinstance(X, pd.DataFrame) else np.asarray(X, np.float32)
        tags = self.tags_ or [f"feature-{i}" for i in range(Xv.shape[-1])]

        output = self._predict_model_space(Xv)
        yv = Xv if y is None else (y.values if isinstance(y, pd.DataFrame) else np.asarray(y))
        target = self._model_space(np.asarray(yv, np.float32))
        target = target[self._offset :][: output.shape[0]]
        inp = Xv[self._offset :][: output.shape[0]]
        if index is not None:
            index = index[: output.shape[0]]

        diff, scaled, tot_u, tot_s = _score_fn(
            ScalerParams(*self.error_scaler_), jnp.asarray(target), jnp.asarray(output)
        )
        return assemble_anomaly_frame(
            tags, inp, output, diff, scaled, tot_u, tot_s, index
        )

    def get_metadata(self) -> Dict[str, Any]:
        md: Dict[str, Any] = {
            "type": type(self).__name__,
            "base_estimator": (
                self.base_estimator.get_metadata()
                if hasattr(self.base_estimator, "get_metadata")
                else repr(self.base_estimator)
            ),
        }
        if self.feature_thresholds_ is not None:
            md["feature-thresholds"] = {
                t: float(v) for t, v in zip(self.tags_ or [], self.feature_thresholds_)
            }
            md["total-anomaly-threshold"] = self.total_threshold_
            md["threshold-method"] = self.threshold_method_ or "exact"
        return md
