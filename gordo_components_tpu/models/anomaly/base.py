"""Abstract anomaly-detector contract (reference:
gordo_components/model/anomaly/base.py, unverified; SURVEY.md §2)."""

import abc
from typing import Optional

import numpy as np
import pandas as pd

from gordo_components_tpu.models.base import GordoBase


class AnomalyDetectorBase(GordoBase, abc.ABC):
    @abc.abstractmethod
    def anomaly(self, X, y=None) -> pd.DataFrame:
        """Score X, returning the multi-level anomaly frame served by
        ``POST /anomaly/prediction``: per-tag scaled/unscaled anomalies and
        total-anomaly columns alongside model input/output."""
