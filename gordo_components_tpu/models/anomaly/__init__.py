"""Anomaly-scoring wrappers (reference parity: gordo_components/model/anomaly/,
unverified — SURVEY.md §2 "model.anomaly")."""

from gordo_components_tpu.models.anomaly.base import AnomalyDetectorBase
from gordo_components_tpu.models.anomaly.diff import DiffBasedAnomalyDetector

__all__ = ["AnomalyDetectorBase", "DiffBasedAnomalyDetector"]
