"""Abstract estimator contract.

Reference parity: ``GordoBase`` in gordo_components/model/base.py
(unverified; SURVEY.md §2 "model.base") — the minimal surface every model
must expose so the builder, serializer, server, and watchman can treat all
models uniformly: ``get_metadata()``, ``score()``, ``get_params()``.
"""

import abc
from typing import Any, Dict, Optional

import numpy as np


class GordoBase(abc.ABC):
    """Base contract for all models in the framework."""

    @abc.abstractmethod
    def fit(self, X: np.ndarray, y: Optional[np.ndarray] = None, **kwargs):
        """Fit the model to X (y defaults per estimator semantics)."""

    @abc.abstractmethod
    def get_metadata(self) -> Dict[str, Any]:
        """JSON-serializable metadata describing configuration and training
        history; threaded into the build artifact and served at
        ``GET /metadata``."""

    @abc.abstractmethod
    def score(self, X: np.ndarray, y: Optional[np.ndarray] = None) -> float:
        """Explained-variance score of the model on (X, y)."""

    def get_params(self, deep=True) -> Dict[str, Any]:
        """Constructor params captured by ``capture_args`` (sklearn-style)."""
        return dict(getattr(self, "_params", {}))

    def set_params(self, **params):
        self._params = {**getattr(self, "_params", {}), **params}
        for k, v in params.items():
            setattr(self, k, v)
        return self

    def __sklearn_tags__(self):
        # sklearn >= 1.6 Pipelines require step tags; delegate to sklearn's
        # default implementation without inheriting its get_params machinery
        from sklearn.base import BaseEstimator as _SkBase

        return _SkBase.__sklearn_tags__(self)


def transform_through_steps(est, X):
    """Apply all but the final step of an sklearn Pipeline-like object —
    the one definition of "walk the preprocessing steps" shared by
    prediction and scoring paths (y never transforms, matching
    ``Pipeline.score``)."""
    for _, step in est.steps[:-1]:
        X = step.transform(X)
    return X


def score_metrics_of(est, X, y=None) -> dict:
    """The reference's full evaluation metric set from any estimator.

    Capability dispatch: native estimators implement ``score_metrics``;
    sklearn Pipelines route through their preprocessing steps to a final
    estimator that may; anything else falls back to ``score()`` (the
    universal sklearn surface), recording explained variance only.
    """
    if hasattr(est, "score_metrics"):
        return est.score_metrics(X, y)
    if hasattr(est, "steps"):
        final = est.steps[-1][1]
        if hasattr(final, "score_metrics"):
            return final.score_metrics(transform_through_steps(est, X), y)
    return {"explained-variance": float(est.score(X, y))}
