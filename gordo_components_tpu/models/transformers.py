"""sklearn-compatible transformers backed by the pure-JAX scaler ops.

The reference's default pipeline uses ``sklearn.preprocessing.MinMaxScaler``
(SURVEY.md §2 "workflow"); that still works here. These equivalents exist so
that (a) fleet-trained stacked scalers (parallel/fleet.py) unstack into
pipeline steps, and (b) the whole scoring path can stay on-device.
"""

from typing import Optional

import jax.numpy as jnp
import numpy as np

from gordo_components_tpu.ops.scaler import (
    ScalerParams,
    fit_minmax,
    fit_standard,
    scaler_inverse_transform,
    scaler_transform,
)
from gordo_components_tpu.utils import capture_args


class _JaxScalerBase:
    _fit_fn = None

    def __init__(self):
        self.scaler_params_: Optional[ScalerParams] = None
        self.n_features_: Optional[int] = None

    def set_fitted(self, params: ScalerParams, n_features: int):
        """Adopt externally fitted (e.g. fleet-stacked) scaler params."""
        self.scaler_params_ = ScalerParams(
            shift=np.asarray(params.shift), scale=np.asarray(params.scale)
        )
        self.n_features_ = n_features
        return self

    def fit(self, X, y=None):
        X = np.asarray(X.values if hasattr(X, "values") else X, dtype=np.float32)
        params = self._fit_params(jnp.asarray(X))
        return self.set_fitted(params, X.shape[-1])

    def _check(self):
        if self.scaler_params_ is None:
            raise RuntimeError(f"{type(self).__name__} is not fitted")

    def transform(self, X):
        self._check()
        Xv = np.asarray(X.values if hasattr(X, "values") else X, dtype=np.float32)
        return np.asarray(
            scaler_transform(ScalerParams(*self.scaler_params_), jnp.asarray(Xv))
        )

    def inverse_transform(self, X):
        self._check()
        Xv = np.asarray(X.values if hasattr(X, "values") else X, dtype=np.float32)
        return np.asarray(
            scaler_inverse_transform(ScalerParams(*self.scaler_params_), jnp.asarray(Xv))
        )

    def fit_transform(self, X, y=None):
        return self.fit(X, y).transform(X)

    def get_params(self, deep=True):
        return dict(getattr(self, "_params", {}))

    def set_params(self, **params):
        self._params = {**getattr(self, "_params", {}), **params}
        return self

    def __sklearn_tags__(self):
        from sklearn.base import BaseEstimator as _SkBase

        return _SkBase.__sklearn_tags__(self)


class JaxMinMaxScaler(_JaxScalerBase):
    @capture_args
    def __init__(self, feature_range=(0.0, 1.0)):
        super().__init__()
        self.feature_range = tuple(feature_range)

    def _fit_params(self, X):
        return fit_minmax(X, feature_range=self.feature_range)


class JaxStandardScaler(_JaxScalerBase):
    @capture_args
    def __init__(self):
        super().__init__()

    def _fit_params(self, X):
        return fit_standard(X)
