"""Model layer: Flax factories + sklearn-compatible estimators + anomaly
wrappers (reference parity: gordo_components/model/, unverified — SURVEY.md
§2)."""

from gordo_components_tpu.models.base import GordoBase
from gordo_components_tpu.models.register import register_model_builder, lookup_factory
from gordo_components_tpu.models.models import (
    AutoEncoder,
    BaseEstimator,
    ConvAutoEncoder,
    LSTMAutoEncoder,
    LSTMForecast,
)
from gordo_components_tpu.models.anomaly import DiffBasedAnomalyDetector

# Reference-era names accepted as aliases so old configs keep working.
KerasAutoEncoder = AutoEncoder
KerasLSTMAutoEncoder = LSTMAutoEncoder
KerasLSTMForecast = LSTMForecast

__all__ = [
    "GordoBase",
    "register_model_builder",
    "lookup_factory",
    "BaseEstimator",
    "AutoEncoder",
    "LSTMAutoEncoder",
    "LSTMForecast",
    "ConvAutoEncoder",
    "DiffBasedAnomalyDetector",
    "KerasAutoEncoder",
    "KerasLSTMAutoEncoder",
    "KerasLSTMForecast",
]
