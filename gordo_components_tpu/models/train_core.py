"""Functional training core: pure init/epoch/predict functions over explicit
state pytrees.

This replaces the reference's ``keras.Model.fit`` inner loop
(gordo_components/model/models.py, unverified; SURVEY.md §3.1 "the COMPUTE
HOT LOOP") with a TPU-idiomatic design:

- one jit'd **epoch** program: on-device shuffle (``jax.random.permutation``)
  + ``lax.scan`` over fixed-size batches — a single XLA computation per
  epoch, no per-batch host round-trips, static shapes throughout;
- ragged data handled by **padding + masks**, never dynamic shapes;
- everything is written to be ``vmap``-ed over a leading model axis: the
  fleet engine (parallel/fleet.py) maps these exact functions over stacked
  params to train thousands of models in one program.
"""

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from gordo_components_tpu.ops.losses import mse_loss


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    rng: jax.Array


def make_optimizer(
    name: str = "adam",
    learning_rate: float = 1e-3,
    inject: bool = False,
    **kwargs,
) -> optax.GradientTransformation:
    """Resolve an optax optimizer by name (reference models compile with
    Keras optimizer names; same strings work here).

    ``inject=True`` wraps the optimizer in ``optax.inject_hyperparams`` so
    ``learning_rate`` lives in the opt STATE instead of being baked into
    the transform — under ``vmap`` that state leaf is a stacked (M,)
    vector, which is how the fleet engine trains members with per-member
    learning rates in ONE program (numerics identical when every member
    shares the base value)."""
    name = name.lower()
    table = {
        "adam": optax.adam,
        "adamw": optax.adamw,
        "sgd": optax.sgd,
        "rmsprop": optax.rmsprop,
        "adagrad": optax.adagrad,
    }
    try:
        factory = table[name]
    except KeyError:
        raise ValueError(f"Unknown optimizer {name!r}; known: {sorted(table)}")
    if inject:
        return optax.inject_hyperparams(factory)(
            learning_rate=learning_rate, **kwargs
        )
    return factory(learning_rate, **kwargs)


def pad_to_batches(
    X: np.ndarray, Y: np.ndarray, batch_size: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Pad (X, Y) with zero rows to a multiple of ``batch_size``.

    Returns (X_pad, Y_pad, mask, n_batches); mask is 1.0 for real rows.
    Padding keeps every batch the same shape so the epoch program compiles
    once regardless of dataset length.
    """
    n = X.shape[0]
    if n == 0:
        raise ValueError("Cannot train on an empty dataset")
    n_batches = max(1, -(-n // batch_size))
    n_pad = n_batches * batch_size
    mask = np.zeros((n_pad,), dtype=np.float32)
    mask[:n] = 1.0
    X_pad = np.zeros((n_pad,) + X.shape[1:], dtype=np.float32)
    X_pad[:n] = X
    Y_pad = np.zeros((n_pad,) + Y.shape[1:], dtype=np.float32)
    Y_pad[:n] = Y
    return X_pad, Y_pad, mask, n_batches


def make_loss_fn(module, loss: str = "mse", kl_weight: float = 1.0) -> Callable:
    """Build ``loss_fn(params, rng, xb, yb, maskb) -> scalar``.

    ``loss='mse'`` covers the reference's autoencoder losses; ``loss='vae'``
    calls the module's ``elbo_terms`` (variational zoo) adding the KL term.
    """
    if loss == "mse":

        def loss_fn(params, rng, xb, yb, maskb):
            pred = module.apply(params, xb)
            return mse_loss(pred, yb, maskb)

    elif loss == "vae":

        def loss_fn(params, rng, xb, yb, maskb):
            recon, kl = module.apply(
                params, xb, method="elbo_terms", rngs={"sample": rng}
            )
            rec = mse_loss(recon, yb, maskb)
            klm = jnp.sum(kl * maskb) / jnp.maximum(jnp.sum(maskb), 1.0)
            return rec + kl_weight * klm

    else:
        raise ValueError(f"Unknown loss {loss!r} (known: mse, vae)")
    return loss_fn


def make_train_fns(
    module,
    optimizer: optax.GradientTransformation,
    batch_size: int,
    loss: str = "mse",
    kl_weight: float = 1.0,
):
    """Returns ``(init_fn, epoch_fn)``.

    - ``init_fn(rng, sample_x) -> TrainState`` (sample_x: one batch-shaped
      row, used only for shape inference)
    - ``epoch_fn(state, X, Y, mask) -> (state, mean_loss)`` where X/Y/mask
      are padded to ``n_batches * batch_size`` rows (see ``pad_to_batches``).
      Performs an on-device shuffle then ``lax.scan`` over batches.

    Both are pure and vmap-able over a leading model axis.
    """
    loss_fn = make_loss_fn(module, loss=loss, kl_weight=kl_weight)

    def init_fn(rng: jax.Array, sample_x: jnp.ndarray) -> TrainState:
        init_rng, state_rng = jax.random.split(rng)
        params = module.init(init_rng, sample_x[None, ...])
        opt_state = optimizer.init(params)
        return TrainState(params=params, opt_state=opt_state, rng=state_rng)

    def epoch_fn(state: TrainState, X, Y, mask):
        n_pad = X.shape[0]
        n_batches = n_pad // batch_size
        # rng consumption is deliberately INDEPENDENT of n_batches (three
        # splits + fold_in per batch index): training a dataset padded to a
        # larger row bucket consumes the same random stream, which is what
        # makes the fleet engine's row-count quantization a true no-op
        rng, perm_rng, batch_base = jax.random.split(state.rng, 3)
        rngs = jax.vmap(lambda i: jax.random.fold_in(batch_base, i))(
            jnp.arange(n_batches)
        )
        # shuffle real rows among themselves and sort padding to the END
        # (stable argsort of prefix-stable uniform keys): real rows stay
        # densely packed in the leading batches — the effective batch size
        # is preserved no matter how much row padding the bucket adds, and
        # any fully-padded trailing batch is skipped as a no-op below.
        keys = jax.random.uniform(perm_rng, (n_pad,))
        perm = jnp.argsort(jnp.where(mask > 0, keys, 2.0))
        Xs = X[perm].reshape((n_batches, batch_size) + X.shape[1:])
        Ys = Y[perm].reshape((n_batches, batch_size) + Y.shape[1:])
        Ms = mask[perm].reshape((n_batches, batch_size))

        def step(carry, batch):
            params, opt_state = carry
            xb, yb, mb, brng = batch
            loss_val, grads = jax.value_and_grad(loss_fn)(params, brng, xb, yb, mb)
            updates, new_opt_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            # all-padding batches must be EXACT no-ops: even zero gradients
            # advance adam's bias-correction count and decay its momentum,
            # which would silently change training dynamics with row padding
            has_real = jnp.sum(mb) > 0
            keep = lambda new, old: jax.tree.map(
                lambda n, o: jnp.where(has_real, n, o), new, old
            )
            # weight the batch loss by its real-row count for a correct
            # dataset-mean when the last batch is partly padding
            return (keep(new_params, params), keep(new_opt_state, opt_state)), (
                loss_val,
                jnp.sum(mb),
            )

        (params, opt_state), (losses, counts) = jax.lax.scan(
            step, (state.params, state.opt_state), (Xs, Ys, Ms, rngs)
        )
        mean_loss = jnp.sum(losses * counts) / jnp.maximum(jnp.sum(counts), 1.0)
        return TrainState(params=params, opt_state=opt_state, rng=rng), mean_loss

    return init_fn, epoch_fn


def gather_window_batch(X, item_idx, lookback: int, target_offset: int):
    """(xb, yb) for a batch of window-start items over raw rows ``X``:
    ``xb`` gathers rows ``[i, i+lookback)``, ``yb`` the target row
    ``i + lookback - 1 + target_offset``. Indices CLIP into range, so
    out-of-range (padded) items gather garbage the caller's item mask must
    zero out — the one shared definition of the windows-as-views index
    arithmetic (train, eval, and fleet error-scaler programs all use it)."""
    rows = X.shape[0]
    widx = jnp.clip(
        item_idx[:, None] + jnp.arange(lookback)[None, :], 0, rows - 1
    )
    yb = X[jnp.clip(item_idx + lookback - 1 + target_offset, 0, rows - 1)]
    return X[widx], yb


def make_seq_train_fns(
    module,
    optimizer: optax.GradientTransformation,
    batch_size: int,
    lookback: int,
    target_offset: int = 0,
    loss: str = "mse",
    kl_weight: float = 1.0,
):
    """Sequence-model variant of :func:`make_train_fns` where windows are
    GATHERED per batch instead of materialized.

    The single-model path materializes ``(n_windows, lookback, f)`` host-side
    and feeds :func:`make_train_fns`; at fleet scale that costs ``lookback``x
    the HBM of the raw rows. Here the epoch program keeps only the raw
    ``(rows, f)`` member block on device and the scan body gathers each
    batch's windows (``X[i : i+lookback]``) on the fly — numerically
    identical (window *i* holds the same rows either way, the shuffle/rng
    scheme is byte-for-byte the one in ``make_train_fns``), but HBM stays
    O(rows) per member.

    - ``init_fn(rng, sample_w) -> TrainState`` (sample_w: one (lookback, f)
      window for shape inference)
    - ``epoch_fn(state, X, Y, mask) -> (state, mean_loss)``: X is the raw
      padded ``(rows_pad, f)`` block; Y is IGNORED (targets derive from X:
      item *i* trains window ``[i, i+lookback)`` against row
      ``i + lookback - 1 + target_offset``); mask is the (items_pad,) item
      validity mask, items_pad a multiple of ``batch_size``.
    """
    loss_fn = make_loss_fn(module, loss=loss, kl_weight=kl_weight)

    def init_fn(rng: jax.Array, sample_w: jnp.ndarray) -> TrainState:
        init_rng, state_rng = jax.random.split(rng)
        params = module.init(init_rng, sample_w[None, ...])
        opt_state = optimizer.init(params)
        return TrainState(params=params, opt_state=opt_state, rng=state_rng)

    def epoch_fn(state: TrainState, X, Y, mask):
        del Y  # targets are rows of X (reconstruction/forecast)
        n_pad = mask.shape[0]
        n_batches = n_pad // batch_size
        rng, perm_rng, batch_base = jax.random.split(state.rng, 3)
        rngs = jax.vmap(lambda i: jax.random.fold_in(batch_base, i))(
            jnp.arange(n_batches)
        )
        keys = jax.random.uniform(perm_rng, (n_pad,))
        perm = jnp.argsort(jnp.where(mask > 0, keys, 2.0))
        idxs = perm.reshape((n_batches, batch_size))
        Ms = mask[perm].reshape((n_batches, batch_size))

        def step(carry, batch):
            params, opt_state = carry
            ib, mb, brng = batch
            # padded items gather clipped garbage; their mask zeroes them out
            xb, yb = gather_window_batch(X, ib, lookback, target_offset)
            loss_val, grads = jax.value_and_grad(loss_fn)(params, brng, xb, yb, mb)
            updates, new_opt_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            has_real = jnp.sum(mb) > 0
            keep = lambda new, old: jax.tree.map(
                lambda n, o: jnp.where(has_real, n, o), new, old
            )
            return (keep(new_params, params), keep(new_opt_state, opt_state)), (
                loss_val,
                jnp.sum(mb),
            )

        (params, opt_state), (losses, counts) = jax.lax.scan(
            step, (state.params, state.opt_state), (idxs, Ms, rngs)
        )
        mean_loss = jnp.sum(losses * counts) / jnp.maximum(jnp.sum(counts), 1.0)
        return TrainState(params=params, opt_state=opt_state, rng=rng), mean_loss

    return init_fn, epoch_fn


def make_seq_gang_epoch(
    module,
    optimizer: optax.GradientTransformation,
    batch_size: int,
    lookback: int,
    target_offset: int = 0,
):
    """Time-major GANG epoch: the whole member axis trains in one
    non-vmapped program whose recurrent scan keeps members innermost
    (ops/seq_scan.py) — ``vmap(epoch_fn)``'s fast-path replacement for
    LSTM buckets.

    ``epoch_fn(states, X, mask) -> (states, (M,) losses)`` over STACKED
    state (leading member axis), X: (M, rows_pad, f), mask: (M,
    items_pad). Per-member semantics are the legacy path's exactly:

    - the shuffle/rng plan is ``make_seq_train_fns``'s byte-for-byte
      (same three splits + fold_in per batch, vmapped per member), so
      every member sees the identical batch sequence;
    - the loss is the per-member masked mean; gradients come from the
      SUM of member losses, which decouples exactly (each member's loss
      depends only on its own parameter rows);
    - the optimizer update and the all-padding-batch no-op guard are
      vmapped per member — elementwise work, not the hot loop.

    The one intentional difference is the forward: the time-major scan
    re-associates the gate matmuls, so parity with the legacy layout is
    fp32-rounding-level, not bitwise (band pinned by
    tests/test_seq_fastpath.py). MSE only — the gang loss needs the
    member-explicit forward, which the variational heads don't have.
    """
    from gordo_components_tpu.ops.seq_scan import lstm_time_major_forward

    def epoch_fn(states: TrainState, X, mask):
        M, n_pad = mask.shape
        n_batches = n_pad // batch_size

        def plan(rng, m):
            rng2, perm_rng, batch_base = jax.random.split(rng, 3)
            rngs = jax.vmap(lambda i: jax.random.fold_in(batch_base, i))(
                jnp.arange(n_batches)
            )
            keys = jax.random.uniform(perm_rng, (n_pad,))
            perm = jnp.argsort(jnp.where(m > 0, keys, 2.0))
            return rng2, perm, rngs

        rng2, perms, rngss = jax.vmap(plan)(states.rng, mask)
        # batch-major so the scan slices one (M, batch) block per step
        idxs = perms.reshape((M, n_batches, batch_size)).transpose(1, 0, 2)
        Ms = (
            jnp.take_along_axis(mask, perms, axis=1)
            .reshape((M, n_batches, batch_size))
            .transpose(1, 0, 2)
        )

        def step(carry, batch):
            params, opt_state = carry
            ib, mb = batch
            xb, yb = jax.vmap(
                gather_window_batch, in_axes=(0, 0, None, None)
            )(X, ib, lookback, target_offset)

            def gang_loss(p):
                preds = lstm_time_major_forward(module, p, xb, kernel="jnp")
                losses = jax.vmap(mse_loss)(preds, yb, mb)
                return jnp.sum(losses), losses

            grads, losses = jax.grad(gang_loss, has_aux=True)(params)
            updates, new_opt = jax.vmap(optimizer.update)(
                grads, opt_state, params
            )
            new_params = optax.apply_updates(params, updates)
            has_real = jnp.sum(mb, axis=1) > 0  # (M,)

            def keep(n, o):
                hr = has_real.reshape((M,) + (1,) * (n.ndim - 1))
                return jnp.where(hr, n, o)

            return (
                jax.tree.map(keep, new_params, params),
                jax.tree.map(keep, new_opt, opt_state),
            ), (losses, jnp.sum(mb, axis=1))

        (params, opt_state), (losses, counts) = jax.lax.scan(
            step, (states.params, states.opt_state), (idxs, Ms)
        )
        mean_loss = jnp.sum(losses * counts, axis=0) / jnp.maximum(
            jnp.sum(counts, axis=0), 1.0
        )
        return (
            TrainState(params=params, opt_state=opt_state, rng=rng2),
            mean_loss,
        )

    return epoch_fn


def make_seq_eval_fn(
    module,
    batch_size: int,
    lookback: int,
    target_offset: int = 0,
    loss: str = "mse",
    kl_weight: float = 1.0,
):
    """``eval_fn(params, X, item_mask) -> mean_loss`` over gathered windows
    (validation loss for sequence fleet members), scan-chunked so HBM never
    holds more than one batch of materialized windows. Uses the SAME loss
    family as training (fixed eval rng, like :func:`make_eval_fn`)."""
    loss_fn = make_loss_fn(module, loss=loss, kl_weight=kl_weight)

    def eval_fn(params, X, mask):
        n_pad = mask.shape[0]
        n_batches = n_pad // batch_size
        idxs = jnp.arange(n_pad).reshape((n_batches, batch_size))
        Ms = mask.reshape((n_batches, batch_size))
        rng = jax.random.PRNGKey(0)

        def step(_, batch):
            ib, mb = batch
            xb, yb = gather_window_batch(X, ib, lookback, target_offset)
            lv = loss_fn(params, rng, xb, yb, mb)
            return None, (lv * jnp.sum(mb), jnp.sum(mb))

        _, (sums, counts) = jax.lax.scan(step, None, (idxs, Ms))
        return jnp.sum(sums) / jnp.maximum(jnp.sum(counts), 1.0)

    return eval_fn


def make_eval_fn(module, batch_size: int, loss: str = "mse", kl_weight: float = 1.0):
    """``eval_fn(state, X, Y, mask) -> mean_loss`` over padded data, no
    parameter update (validation loss / early stopping)."""
    loss_fn = make_loss_fn(module, loss=loss, kl_weight=kl_weight)

    def eval_fn(state: TrainState, X, Y, mask):
        n_batches = X.shape[0] // batch_size
        Xs = X.reshape((n_batches, batch_size) + X.shape[1:])
        Ys = Y.reshape((n_batches, batch_size) + Y.shape[1:])
        Ms = mask.reshape((n_batches, batch_size))
        rng = jax.random.PRNGKey(0)

        def step(_, batch):
            xb, yb, mb = batch
            return None, (loss_fn(state.params, rng, xb, yb, mb), jnp.sum(mb))

        _, (losses, counts) = jax.lax.scan(step, None, (Xs, Ys, Ms))
        return jnp.sum(losses * counts) / jnp.maximum(jnp.sum(counts), 1.0)

    return eval_fn


def batched_apply(
    module, params, X: np.ndarray, batch_size: int = 4096
) -> np.ndarray:
    """Run ``module.apply`` over X in fixed-size chunks.

    Pads to a multiple of ``batch_size`` and scans, so inference compiles
    once per (batch_size, feature-shape) regardless of request length —
    essential for the server, where request sizes vary per call.
    """
    n = X.shape[0]
    if n == 0:
        raise ValueError("empty input")
    eff_bs = min(batch_size, _next_pow2(n))
    n_batches = -(-n // eff_bs)
    n_pad = n_batches * eff_bs
    X_pad = np.zeros((n_pad,) + X.shape[1:], dtype=np.float32)
    X_pad[:n] = X
    out = _scan_apply(module, params, jnp.asarray(X_pad), eff_bs)
    return np.asarray(out)[:n]


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# jit cache for batched_apply: a fresh @jax.jit closure per call would
# recompile on EVERY predict (the server's per-model hot path). Keyed by
# module identity (modules are rebuilt once per estimator and reused) and
# batch size; the module object is pinned in the value so its id can't be
# recycled while the entry lives.
_apply_cache: dict = {}


def _scan_apply(module, params, X_pad, batch_size):
    key = (id(module), batch_size)
    entry = _apply_cache.get(key)
    if entry is None or entry[0] is not module:

        @jax.jit
        def run(params, X_pad):
            n_batches = X_pad.shape[0] // batch_size
            Xs = X_pad.reshape((n_batches, batch_size) + X_pad.shape[1:])

            def step(_, xb):
                return None, module.apply(params, xb)

            _, out = jax.lax.scan(step, None, Xs)
            return out.reshape((n_batches * batch_size,) + out.shape[2:])

        if len(_apply_cache) >= 512:  # bound memory on pathological churn
            _apply_cache.clear()
        entry = (module, run)
        _apply_cache[key] = entry
    return entry[1](params, X_pad)
