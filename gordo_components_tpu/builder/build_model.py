"""Train-one-model pipeline.

Reference parity: ``build_model`` / ``provide_saved_model`` /
``calculate_model_key`` (gordo_components/builder/build_model.py,
unverified; SURVEY.md §2 "builder", §3.1): dataset → pipeline instantiation
(serializer) → optional TimeSeriesSplit cross-validation → fit → metadata
assembly → artifact dump, with a config-hash build cache so rerunning a
fleet skips machines whose artifact already exists — the semantics that make
10k-model reruns cheap (SURVEY.md §5 "Checkpoint/resume").
"""

import hashlib
import json
import logging
import os
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np
import pandas as pd

from gordo_components_tpu import __version__
from gordo_components_tpu.dataset import get_dataset
from gordo_components_tpu.models.base import score_metrics_of
from gordo_components_tpu import serializer
from gordo_components_tpu.utils import metadata_timestamp
from gordo_components_tpu.utils.profiling import device_memory_stats, maybe_profile

logger = logging.getLogger(__name__)


def build_model(
    name: str,
    model_config: Dict[str, Any],
    data_config: Dict[str, Any],
    metadata: Optional[Dict[str, Any]] = None,
    evaluation_config: Optional[Dict[str, Any]] = None,
) -> Tuple[Any, Dict[str, Any]]:
    """Build and train a single model; returns ``(model, metadata)``.

    ``evaluation_config``: ``{"cv_mode": "full_build" | "cross_val_only",
    "n_splits": 3}`` — TimeSeriesSplit cross-validation with
    explained-variance scores recorded into metadata (reference behavior).
    """
    metadata = dict(metadata or {})
    evaluation_config = _normalize_evaluation(evaluation_config)

    t0 = time.time()
    dataset = get_dataset(dict(data_config))
    X, y = dataset.get_data()
    data_elapsed = time.time() - t0

    model = serializer.from_definition(model_config)

    cv_meta: Dict[str, Any] = {}
    if _wants_cv(evaluation_config):
        cv_meta = _cross_validate(
            model_config, X, y, int(evaluation_config.get("n_splits", 3))
        )

    t1 = time.time()
    trained = False
    if evaluation_config["cv_mode"] != "cross_val_only":
        with maybe_profile(f"build-{name}"):
            model.fit(X, y)
        trained = True
    fit_elapsed = time.time() - t1

    build_metadata = {
        "name": name,
        "gordo_components_tpu_version": __version__,
        "checked_at": metadata_timestamp(),
        "dataset": dataset.get_metadata(),
        "model": {
            "model_config": model_config,
            "data_query_duration_sec": data_elapsed,
            "model_training_duration_sec": fit_elapsed,
            "trained": trained,
            "device_memory": device_memory_stats(),
            **(model.get_metadata() if hasattr(model, "get_metadata") else _pipeline_metadata(model)),
        },
        "user-defined": metadata,
    }
    if cv_meta:
        build_metadata["model"]["cross-validation"] = cv_meta
    return model, build_metadata


def _normalize_evaluation(evaluation_config: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    return {"cv_mode": "full_build", **(evaluation_config or {})}


def _wants_cv(evaluation_config: Dict[str, Any]) -> bool:
    wants = evaluation_config["cv_mode"] == "cross_val_only" or bool(
        evaluation_config.get("cross_validation", False)
    )
    return wants and int(evaluation_config.get("n_splits", 3)) > 0


def cached_cv_satisfied(cached_dir: str, evaluation: Dict[str, Any]) -> bool:
    """True iff ``cached_dir``'s artifact satisfies ``evaluation`` (already
    normalized): either no CV is requested, or the recorded per-fold scores
    match the requested fold count. The ONE cache-satisfaction contract —
    single builds (provide_saved_model) and gang reruns (fleet_build) must
    hit/miss the registry identically for the same machine."""
    if not _wants_cv(evaluation):
        return True
    folds = (
        serializer.load_metadata(cached_dir)
        .get("model", {})
        .get("cross-validation", {})
        .get("explained-variance", {})
        .get("per-fold", [])
    )
    return len(folds) == int(evaluation.get("n_splits", 3))


def _pipeline_metadata(model) -> Dict[str, Any]:
    """Metadata for sklearn Pipelines wrapping our estimators."""
    if hasattr(model, "steps"):
        final = model.steps[-1][1]
        if hasattr(final, "get_metadata"):
            return {"final_step": final.get_metadata()}
    return {}


def summarize_cv_folds(folds) -> Dict[str, Any]:
    """Per-metric ``{mean, std, per-fold}`` summary over per-fold metric
    dicts — one shared shape for the single-build and gang CV paths, so
    their metadata stays key-identical (parity-tested)."""
    out: Dict[str, Any] = {}
    for key in folds[0] if folds else ():
        vals = [float(f[key]) for f in folds]
        out[key] = {
            "mean": float(np.mean(vals)),
            "std": float(np.std(vals)),
            "per-fold": vals,
        }
    return out


def _cross_validate(model_config, X, y, n_splits: int) -> Dict[str, Any]:
    """TimeSeriesSplit CV recording the reference's full metric set per
    fold (explained variance, r2, MSE, MAE — one prediction pass feeds
    all four). Each fold trains a fresh instance deserialized from config
    (sidestepping sklearn ``clone`` constraints on captured-kwargs
    estimators)."""
    from sklearn.model_selection import TimeSeriesSplit

    Xv = X.values if hasattr(X, "values") else np.asarray(X)
    yv = None if y is None else (y.values if hasattr(y, "values") else np.asarray(y))
    folds = []
    t0 = time.time()
    for fold, (train_idx, test_idx) in enumerate(TimeSeriesSplit(n_splits=n_splits).split(Xv)):
        fold_model = serializer.from_definition(model_config)
        fold_model.fit(Xv[train_idx], None if yv is None else yv[train_idx])
        # capability dispatch: bare sklearn Pipelines/estimators (legal
        # top-level configs) fall back to score()'s explained variance
        metrics = score_metrics_of(
            fold_model, Xv[test_idx], None if yv is None else yv[test_idx]
        )
        folds.append(metrics)
        logger.info(
            "CV fold %d explained variance: %.4f",
            fold, metrics["explained-variance"],
        )
    return {"cv_duration_sec": time.time() - t0, **summarize_cv_folds(folds)}


def calculate_model_key(
    name: str,
    model_config: Dict[str, Any],
    data_config: Dict[str, Any],
    metadata: Optional[Dict[str, Any]] = None,
) -> str:
    """Deterministic cache key over (name, configs, framework version)."""
    payload = json.dumps(
        {
            "name": name,
            "model_config": model_config,
            "data_config": _jsonable_config(data_config),
            "metadata": metadata or {},
            "version": __version__,
        },
        sort_keys=True,
        default=str,
    )
    return hashlib.sha256(payload.encode()).hexdigest()


def _jsonable_config(config: Dict[str, Any]) -> Dict[str, Any]:
    out = {}
    for k, v in config.items():
        if hasattr(v, "to_dict"):
            out[k] = v.to_dict()
        elif isinstance(v, pd.Timestamp):
            out[k] = v.isoformat()
        else:
            out[k] = v
    return out


def provide_saved_model(
    name: str,
    model_config: Dict[str, Any],
    data_config: Dict[str, Any],
    metadata: Optional[Dict[str, Any]] = None,
    output_dir: str = "./model-output",
    model_register_dir: Optional[str] = None,
    replace_cache: bool = False,
    evaluation_config: Optional[Dict[str, Any]] = None,
) -> str:
    """Build-or-reuse: if a registered artifact exists for this config hash,
    return it; else build, save to ``output_dir``, and register. Returns the
    artifact directory path (reference semantics)."""
    cache_key = calculate_model_key(name, model_config, data_config, metadata)

    # The cache key excludes evaluation_config, so a cached artifact only
    # satisfies a CV-requesting run if it already carries CV metadata; a
    # cross_val_only run never takes the cache (its contract is an untrained
    # evaluation artifact, not a trained one).
    evaluation = _normalize_evaluation(evaluation_config)
    cross_val_only = evaluation["cv_mode"] == "cross_val_only"

    if model_register_dir and not replace_cache and not cross_val_only:
        cached = os.path.join(model_register_dir, cache_key)
        if os.path.isdir(cached) and os.path.exists(os.path.join(cached, "model.pkl")):
            # the cached CV must match the requested fold count, or the
            # hit would report stats for a CV the caller didn't ask for
            if cached_cv_satisfied(cached, evaluation):
                logger.info("Model %s found in build cache: %s", name, cached)
                _mirror_artifact(cached, output_dir)
                return cached

    model, build_metadata = build_model(
        name, model_config, data_config, metadata, evaluation_config
    )
    build_metadata["model"]["model_builder_cache_key"] = cache_key

    # Only TRAINED models enter the build-cache registry: a cross_val_only
    # run must not register an unfitted artifact under the same key a full
    # build would hit.
    register = model_register_dir and build_metadata["model"]["trained"]
    dest = os.path.join(model_register_dir, cache_key) if register else output_dir
    serializer.dump(model, dest, metadata=build_metadata)
    _mirror_artifact(dest, output_dir)
    logger.info("Model %s built and saved to %s", name, dest)
    return dest


def _mirror_artifact(src_dir: str, output_dir: str) -> None:
    """Surface a (possibly cached) registry artifact at the requested output
    location — reruns must still populate the serving volume."""
    if os.path.abspath(src_dir) == os.path.abspath(output_dir):
        return
    import shutil

    os.makedirs(output_dir, exist_ok=True)
    for fname in os.listdir(src_dir):
        shutil.copy2(os.path.join(src_dir, fname), os.path.join(output_dir, fname))
