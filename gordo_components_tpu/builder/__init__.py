"""Builder layer (reference parity: gordo_components/builder/build_model.py,
unverified — SURVEY.md §2 "builder")."""

from gordo_components_tpu.builder.build_model import (
    build_model,
    calculate_model_key,
    provide_saved_model,
)

__all__ = ["build_model", "provide_saved_model", "calculate_model_key"]
