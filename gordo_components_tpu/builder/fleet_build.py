"""Fleet builder: train a gang of machines in one process.

This is the builder-pod entrypoint for gang-scheduled TPU jobs
(workflow/scheduler.py): where the reference runs ``build_model`` once per
pod, a gang job loads every member's dataset host-side, then trains all
*fleetable* members in one vmap/shard_map program (parallel/fleet.py) and
falls back to the per-machine ``provide_saved_model`` path for bespoke
model configs — so arbitrary reference-style configs still work inside a
gang.
"""

import copy
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from gordo_components_tpu import serializer
from gordo_components_tpu.builder.build_model import (
    _mirror_artifact,
    _normalize_evaluation,
    _wants_cv,
    cached_cv_satisfied,
    calculate_model_key,
    provide_saved_model,
)
from gordo_components_tpu.parallel.fleet import (
    DEFAULT_LEARNING_RATE,
    FleetTrainer,
    _family_defaults,
    _target_offset_for,
)
from gordo_components_tpu.observability import get_registry
from gordo_components_tpu.observability.tracing import (
    chrome_trace,
    get_tracer,
    use_trace,
)
from gordo_components_tpu.resilience.faults import faultpoint
from gordo_components_tpu.utils import metadata_timestamp
from gordo_components_tpu.utils.staging import stage_members
from gordo_components_tpu.workflow.config import Machine

logger = logging.getLogger(__name__)

# chaos site (tests/test_chaos.py): one poisoned hparam group's training
# must degrade to a partial manifest, never abort the whole gang
_FP_GROUP = faultpoint("fleet_build.group")

# cross-arch gang scheduling (ISSUE 20): groups at or below this member
# count are "small" — their wall time is dominated by host-side work
# (tracing, compile, stack/unstack), so overlapping them pays; larger
# groups saturate the device alone and stay serial
GANG_SMALL_MAX = 32
GANG_WIDTH_ENV = "GORDO_GANG_WIDTH"


def resolve_gang_width(n_groups: int) -> int:
    """Worker-thread count for the small-group gang scheduler. Env
    ``GORDO_GANG_WIDTH``: an integer pins it; ``auto``/unset picks
    min(4, n_groups) when more than one accelerator device is present
    (overlap is free there) and 1 on a single-device host — the CPU test
    rigs keep today's strictly serial, deterministic schedule unless a
    test opts in explicitly."""
    raw = (os.environ.get(GANG_WIDTH_ENV) or "auto").strip().lower()
    if raw not in ("", "auto"):
        width = int(raw)
        if width < 1:
            raise ValueError(f"{GANG_WIDTH_ENV} must be >= 1, got {width}")
        return min(width, max(1, n_groups))
    import jax

    if jax.device_count() > 1 or jax.default_backend() in ("tpu", "gpu"):
        return min(4, max(1, n_groups))
    return 1


class _LockedHeartbeat:
    """Serializes heartbeat writes when gang worker threads report
    concurrently — the state file update is read-modify-write."""

    def __init__(self, hb):
        self._hb = hb
        self._lock = threading.Lock()

    def update(self, **kw):
        with self._lock:
            self._hb.update(**kw)

    def finish(self, *a, **kw):
        with self._lock:
            self._hb.finish(*a, **kw)


class FleetBuildReport(Dict[str, str]):
    """``build_fleet``'s return value: name -> artifact dir, exactly the
    mapping callers have always received, PLUS the partial-build record —
    ``failed`` maps members whose group (or bespoke build) exhausted its
    retries to the error string, and ``group_retries`` counts retry
    attempts that eventually succeeded. ``manifest()`` renders the
    partial-manifest schema the CLI ships."""

    SCHEMA = "gordo.fleet-build.manifest/v1"

    def __init__(self, *args: Any, **kwargs: Any):
        super().__init__(*args, **kwargs)
        self.failed: Dict[str, str] = {}
        self.group_retries: int = 0
        self.gang_width: int = 1  # small-group scheduler width used

    def manifest(self) -> Dict[str, Any]:
        return {
            "schema": self.SCHEMA,
            "built": dict(self),
            "failed": dict(self.failed),
            "n_built": len(self),
            "n_failed": len(self.failed),
            "group_retries": self.group_retries,
            "gang_width": self.gang_width,
        }


def _finish_build_trace(trace, output_dir: str, **attrs: Any) -> None:
    """Close the build trace and persist it as Chrome trace-event JSON
    next to the build manifest — best-effort (the trace is diagnostics,
    never worth failing a build over), and written on the crash path too:
    a flight recorder is most valuable for the build that died."""
    if trace is None:
        return
    trace.finish(**attrs)
    try:
        os.makedirs(output_dir, exist_ok=True)
        path = os.path.join(output_dir, "build_trace.json")
        with open(path, "w") as f:
            json.dump(chrome_trace([trace]), f)
        logger.info(
            "build trace (fit/compile/checkpoint spans per bucket) -> %s "
            "(trace_id=%s; open in chrome://tracing or Perfetto)",
            path, trace.trace_id,
        )
    except Exception:
        logger.warning("failed to write build trace", exc_info=True)


def _build_counters():
    """Builder-process metrics (observability/): how many models were
    built, how, and how many the cache spared — progress a restarted gang
    pod's registry snapshot makes visible next to its heartbeats."""
    reg = get_registry()
    return {
        "built": reg.counter(
            "gordo_build_models_built_total",
            "Models built (artifact written)", ("path",),
        ),
        "cache_hits": reg.counter(
            "gordo_build_cache_hits_total",
            "Builds skipped because the register cache satisfied them",
        ),
    }

_AE_PATHS = (
    "gordo_components_tpu.models.AutoEncoder",
    "gordo_components_tpu.models.models.AutoEncoder",
    "gordo_components.model.models.KerasAutoEncoder",
)
# sequence families the fleet engine also gang-trains (gather-windowed
# programs, parallel/fleet.py); reference-era aliases included
_SEQ_PATHS = {
    "LSTMAutoEncoder": (
        "gordo_components_tpu.models.LSTMAutoEncoder",
        "gordo_components_tpu.models.models.LSTMAutoEncoder",
        "gordo_components.model.models.KerasLSTMAutoEncoder",
    ),
    "LSTMForecast": (
        "gordo_components_tpu.models.LSTMForecast",
        "gordo_components_tpu.models.models.LSTMForecast",
        "gordo_components.model.models.KerasLSTMForecast",
    ),
    "ConvAutoEncoder": (
        "gordo_components_tpu.models.ConvAutoEncoder",
        "gordo_components_tpu.models.models.ConvAutoEncoder",
    ),
}
_DET_PATHS = (
    "gordo_components_tpu.models.DiffBasedAnomalyDetector",
    "gordo_components_tpu.models.anomaly.DiffBasedAnomalyDetector",
    "gordo_components.model.anomaly.DiffBasedAnomalyDetector",
)
_SCALER_PATHS = (
    "sklearn.preprocessing.MinMaxScaler",
    "gordo_components_tpu.models.transformers.JaxMinMaxScaler",
)
_STANDARD_SCALER_PATHS = (
    "sklearn.preprocessing.StandardScaler",
    "gordo_components_tpu.models.transformers.JaxStandardScaler",
)

# Estimator kwargs the fleet path honors with semantics identical to the
# single-build path: FleetTrainer's own training knobs (including
# validation_split, whose val-loss drives the per-member ES mask, and
# loss/kl_weight, resolved per module exactly like BaseEstimator) plus the
# factory surfaces. Anything else (e.g. data_parallel) must take the
# single-build path rather than be silently dropped.
_TRAINER_KEYS = frozenset(
    {
        "kind", "epochs", "batch_size", "learning_rate", "optimizer",
        "early_stopping_patience", "early_stopping_min_delta",
        "validation_split", "seed", "compute_dtype", "quantize_rows",
        "loss", "kl_weight",
    }
)
# NOTE: "input_scaler" is deliberately NOT in _TRAINER_KEYS: it is injected
# by extract_fleetable from the pipeline's scaler STEP, never accepted as a
# user-supplied AutoEncoder kwarg (which must fail the fleetable check and
# then fail loudly on the single-build path).
_FACTORY_KEYS = frozenset(
    {
        "encoding_dim", "decoding_dim", "encoding_func", "decoding_func",
        "out_func", "dims", "funcs", "encoding_layers", "compression_factor",
        "func", "channels", "kernel_size", "latent_dim", "conv_impl",
    }
)


def extract_fleetable(model_config: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """If ``model_config`` is EXACTLY the canonical anomaly pipeline —
    ``DiffBasedAnomalyDetector(base_estimator=Pipeline(scaler,
    estimator))`` with a default-kwargs MinMax/Standard scaler step — return
    the estimator kwargs for FleetTrainer, augmented with the honored
    routing kwargs (``input_scaler`` for the z-score scaler, ``model_type``
    for sequence families, ``threshold_quantile``/``require_thresholds``
    detector knobs — quantile thresholds are exact for the dense family
    and histogram-approximate (one-bin-width tolerance) for sequence
    families); else None (single-build path).

    The check is deliberately strict: the fleet engine fits exactly the
    default min-max or z-score affine, so any config that deviates (unknown
    detector or estimator kwargs, scaler kwargs, no scaler step, bare base
    estimator) must take the single-build path to keep identical semantics.
    """
    if not isinstance(model_config, dict) or len(model_config) != 1:
        return None
    (path, kwargs), = model_config.items()
    kwargs = kwargs or {}
    if path not in _DET_PATHS:
        return None
    det_kwargs = {k: v for k, v in kwargs.items() if k != "base_estimator"}
    if set(det_kwargs) - {"threshold_quantile", "require_thresholds"}:
        return None  # detector overrides the fleet can't honor
    base = kwargs.get("base_estimator")
    if not (isinstance(base, dict) and len(base) == 1):
        return None
    (bpath, bkwargs), = base.items()
    if bpath != "sklearn.pipeline.Pipeline":
        return None
    steps = (bkwargs or {}).get("steps", [])
    inner = []
    for s in steps:
        if isinstance(s, (list, tuple)) and len(s) == 2:
            s = s[1]
        inner.append(s)
    scaler_kind = None
    if len(inner) == 2 and _is_path(inner[0], _SCALER_PATHS):
        scaler_kind = "minmax"
    elif len(inner) == 2 and _is_path(inner[0], _STANDARD_SCALER_PATHS):
        scaler_kind = "standard"
    if scaler_kind is not None:
        est = _estimator_kwargs(inner[1])
        if est is None:
            return None
        model_type, ae = est
        honored = _TRAINER_KEYS | _FACTORY_KEYS
        if model_type != "AutoEncoder":
            honored = honored | {"lookback_window"}
        if set(ae) - honored:
            return None  # kwargs the trainer can't honor identically
        if scaler_kind != "minmax":
            ae = dict(ae, input_scaler=scaler_kind)
        if model_type != "AutoEncoder":
            ae = dict(ae, model_type=model_type)
        if det_kwargs:
            ae = dict(ae, **det_kwargs)
        return ae
    return None


def _is_path(defn, paths) -> bool:
    """True iff ``defn`` names one of ``paths`` with NO constructor kwargs —
    a scaler with e.g. a custom feature_range must not take the fleet path
    (which always fits the default (0, 1) min-max)."""
    if isinstance(defn, str):
        return defn in paths
    if isinstance(defn, dict) and len(defn) == 1:
        (path, kwargs), = defn.items()
        return path in paths and not kwargs
    return False


def _estimator_kwargs(defn) -> Optional[Tuple[str, Dict[str, Any]]]:
    """(model_type, kwargs) for a recognized estimator definition, else
    None. model_type is the registry namespace FleetTrainer trains."""
    if isinstance(defn, str):
        path, kwargs = defn, {}
    elif isinstance(defn, dict) and len(defn) == 1:
        (path, kwargs), = defn.items()
        kwargs = dict(kwargs or {})
    else:
        return None
    if path in _AE_PATHS:
        return "AutoEncoder", kwargs
    for model_type, paths in _SEQ_PATHS.items():
        if path in paths:
            return model_type, kwargs
    return None


def _group_key(ae_kwargs: Dict[str, Any]) -> Tuple:
    """Gang membership key. ``learning_rate`` and (the VALUE of)
    ``early_stopping_patience`` are excluded: FleetTrainer stacks them as
    per-member (M,) vectors inside one program (VERDICT r3 next #7 /
    SURVEY §7 hard part 4), so machines differing only in those knobs
    must share a gang instead of shrinking vmap width. ES *presence*
    still splits — ES-on and ES-off members run different programs."""
    items = []
    for k, v in sorted(ae_kwargs.items()):
        if k == "learning_rate":
            continue
        if k == "early_stopping_patience":
            if v is not None:  # explicit None == omitted == ES off
                items.append((k, True))
            continue
        items.append((k, repr(v)))
    return tuple(items)


def _member_hparams_of(ae_kwargs: Dict[str, Any]) -> Dict[str, Any]:
    """The per-member vector knobs, with omissions normalized to the
    ENGINE defaults — a machine that omitted learning_rate must train at
    the default, not at whichever rate the group's first machine chose."""
    hp = {
        "learning_rate": float(
            ae_kwargs.get("learning_rate", DEFAULT_LEARNING_RATE)
        )
    }
    if ae_kwargs.get("early_stopping_patience") is not None:
        hp["early_stopping_patience"] = int(ae_kwargs["early_stopping_patience"])
    return hp


# CV fold members ride the SAME stacked member axis as real members — the
# separator cannot occur in machine names (NUL is not config-expressible)
_CV_SEP = "\x00cv\x00"


def _cv_key(name: str, fold: int) -> str:
    return f"{_CV_SEP}{fold}{_CV_SEP}{name}"


def _cache_satisfies_cv(cached: str, machine: Machine) -> bool:
    return cached_cv_satisfied(
        cached, _normalize_evaluation(machine.evaluation or None)
    )


def _plan_cv_folds(
    pending: List[Machine],
    member_data: Dict[str, Any],
    ae_kwargs: Dict[str, Any],
) -> Tuple[Dict[str, Tuple[List, np.ndarray]], Dict[str, np.ndarray], List[Machine]]:
    """TimeSeriesSplit fold plan for every CV-requesting member of a gang.

    Returns ``(plan_by_name, fold_member_data, infeasible)`` where the plan
    maps name -> (splits, float32 member array — reused by the scoring
    pass so the full history matrix converts once): fold
    training slices become extra stacked members (the TPU-first answer to
    per-machine ``evaluation`` blocks — folds vmap along the member axis,
    so k-fold CV widens the gang program instead of multiplying builds;
    VERDICT r3 next #2). A machine whose folds are too short for this
    family (sequence warmup) is returned as infeasible and must take the
    single-build path, which raises the same errors a reference-style
    single build would.
    """
    from sklearn.model_selection import TimeSeriesSplit

    model_type = ae_kwargs.get("model_type", "AutoEncoder")
    t_offset = _target_offset_for(model_type)
    if t_offset is None:
        min_rows = 1
    else:
        lb = ae_kwargs.get("lookback_window")
        if lb is None:
            _, lb = _family_defaults(model_type)
        min_rows = int(lb) + t_offset  # shortest slice fit/score accepts

    plan_by_name: Dict[str, Tuple[List, np.ndarray]] = {}
    fold_data: Dict[str, np.ndarray] = {}
    infeasible: List[Machine] = []
    for machine in pending:
        ev = _normalize_evaluation(machine.evaluation or None)
        if not _wants_cv(ev):
            continue
        X = member_data[machine.name]
        Xv = np.asarray(X.values if hasattr(X, "values") else X, np.float32)
        n_splits = int(ev.get("n_splits", 3))
        try:
            splits = list(TimeSeriesSplit(n_splits=n_splits).split(Xv))
        except ValueError:
            splits = None
        if splits is None or any(
            len(tr) < min_rows or len(te) < min_rows for tr, te in splits
        ):
            infeasible.append(machine)
            continue
        plan_by_name[machine.name] = (splits, Xv)
        for fold, (tr, _te) in enumerate(splits):
            fold_data[_cv_key(machine.name, fold)] = Xv[tr]
    return plan_by_name, fold_data, infeasible


def _score_cv_folds(
    plan_by_name: Dict[str, Tuple[List, np.ndarray]],
    fleet_models: Dict[str, Any],
) -> Dict[str, Dict[str, Any]]:
    """The reference's full metric set per fold (explained variance, r2,
    MSE, MAE), scored with each fold member converted to the SAME
    detector pipeline the single-build CV scores — metadata keys
    identical to build_model._cross_validate."""
    from gordo_components_tpu.builder.build_model import summarize_cv_folds

    out: Dict[str, Dict[str, Any]] = {}
    for name, (splits, Xv) in plan_by_name.items():
        t0 = time.time()
        folds = []
        for fold, (_tr, te) in enumerate(splits):
            det = fleet_models[_cv_key(name, fold)].to_estimator()
            folds.append(det.score_metrics(Xv[te]))
        out[name] = {
            "cv_duration_sec": time.time() - t0,
            # fold training amortized inside the gang program; this wall
            # time covers only the scoring pass
            "fleet_cv": True,
            **summarize_cv_folds(folds),
        }
    return out


def build_fleet(
    machines: List[Machine],
    output_dir: str,
    model_register_dir: Optional[str] = None,
    replace_cache: bool = False,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 1,
    distributed: bool = False,
    state_dir: Optional[str] = None,
    gang_id: Optional[str] = None,
    group_retries: Optional[int] = None,
) -> "FleetBuildReport":
    """Build every machine; returns a :class:`FleetBuildReport` —
    name -> artifact dir (a plain dict to existing callers) with the
    partial-build record on ``.failed``.

    Fleetable machines with identical AutoEncoder kwargs train together in
    one FleetTrainer program; everything else falls back to the single-model
    builder. Cache semantics (config-hash keyed) apply to both paths.
    ``checkpoint_dir`` enables mid-training preemption recovery for the
    fleet groups (parallel/checkpoint.py): a restarted gang resumes its
    interrupted epoch loop instead of retraining from scratch.
    ``state_dir`` enables gang heartbeats (workflow/gang_state.py): phase
    and per-epoch progress on a shared volume for watchman to aggregate.

    Failure isolation: a bespoke machine whose single build fails, or an
    hparam group whose gang training fails ``group_retries + 1`` times
    (default 1 retry; env ``GORDO_BUILD_GROUP_RETRIES``), records its
    member(s) under ``.failed`` and every OTHER machine/group still
    ships — one poisoned config must not abort a 10k-member gang. The
    heartbeat ends in phase ``done`` (nothing failed), ``partial`` (some
    members failed), or ``failed`` (nothing built).
    """
    from gordo_components_tpu.resilience import configure_from_env

    configure_from_env()  # GORDO_FAULTS: chaos runs drive the build path too
    if group_retries is None:
        group_retries = int(os.environ.get("GORDO_BUILD_GROUP_RETRIES", "1"))
    # one build trace per build_fleet run (observability/tracing.py):
    # fleet groups record per-bucket fit/compile/checkpoint spans into it
    # and the Chrome trace-event export lands next to the build manifest.
    # force=True: a build is one trace, not head-sampled traffic —
    # GORDO_TRACE_SAMPLE=0 still disables tracing entirely
    tracer = get_tracer()
    trace = tracer.start_trace("fleet_build", force=True)
    results = FleetBuildReport()
    fleet_groups: Dict[Tuple, List[Tuple[Machine, Dict[str, Any]]]] = {}
    trainer_mesh = None
    dist_ok = False
    counters = _build_counters()  # once: the families are process-wide

    if distributed:
        # pod-scale gang: every host runs this same function; each owns a
        # deterministic member slice and trains it independently — zero DCN
        # traffic during training (parallel/distributed.py)
        from gordo_components_tpu.parallel.distributed import (
            initialize_distributed,
            partition_members,
        )

        dist_ok = initialize_distributed()
        if dist_ok:
            # members are partitioned per host, so each host's member stack
            # is host-local and differently shaped: the trainer mesh must
            # span only THIS host's devices. A global mesh (jax.devices()
            # spans the whole pod under jax.distributed) would device_put
            # host-local data onto a non-addressable sharding and trace
            # per-host-different programs — an SPMD violation. The global
            # runtime is kept only for the rendezvous/partition step.
            import jax

            from gordo_components_tpu.parallel.mesh import fleet_mesh

            trainer_mesh = fleet_mesh(devices=jax.local_devices())
        else:
            # misconfigured rendezvous silently degrading would make EVERY
            # worker own the full fleet: duplicated training + racing
            # artifact writes. Be loud; proceed only because a genuine
            # single-host launch with --distributed is legitimate.
            logger.warning(
                "--distributed requested but running single-process "
                "(no coordinator found / rendezvous not configured): this "
                "process will build ALL %d members. If other workers were "
                "launched the same way they are duplicating this work.",
                len(machines),
            )
        owned = set(partition_members([m.name for m in machines]))
        skipped = [m.name for m in machines if m.name not in owned]
        if skipped:
            logger.info(
                "Distributed gang: this host owns %d/%d members",
                len(owned), len(machines),
            )
        machines = [m for m in machines if m.name in owned]

    heartbeat = None
    if state_dir:
        from gordo_components_tpu.workflow.gang_state import GangHeartbeat

        # created AFTER member partitioning: n_machines reflects this
        # host's slice, and in a multi-host gang the template-pinned
        # GANG_ID is suffixed per host so peers don't clobber each other's
        # heartbeat (one host finishing must not mask the rest)
        if gang_id and dist_ok:
            import jax

            gang_id = f"{gang_id}-host{jax.process_index()}"
        heartbeat = _LockedHeartbeat(GangHeartbeat(state_dir, gang_id))
        heartbeat.update(
            phase="starting", n_machines=len(machines), built=0,
            distributed=bool(distributed),
        )

    try:
        for machine in machines:
            ae_kwargs = extract_fleetable(machine.model)
            # the fleet engine trains X -> X (reconstruction); a dataset
            # declaring target tags supervises X -> y, so it must take the
            # single-build path (which honors y) rather than silently
            # training the wrong objective
            if ae_kwargs is not None and (machine.dataset or {}).get(
                "target_tag_list"
            ):
                ae_kwargs = None
            # cross_val_only's contract is an evaluation-only (untrained)
            # artifact — the single-build path owns that; fleet groups
            # handle the full_build+cross_validation case by vmapping folds
            if (
                ae_kwargs is not None
                and _normalize_evaluation(machine.evaluation or None)["cv_mode"]
                == "cross_val_only"
            ):
                ae_kwargs = None
            if ae_kwargs is None:
                logger.info(
                    "Machine %s: bespoke config, single-build path", machine.name
                )
                try:
                    results[machine.name] = provide_saved_model(
                        machine.name,
                        machine.model,
                        machine.dataset,
                        machine.metadata,
                        output_dir=os.path.join(output_dir, machine.name),
                        model_register_dir=model_register_dir,
                        replace_cache=replace_cache,
                        evaluation_config=machine.evaluation or None,
                    )
                except Exception as exc:
                    # per-machine isolation on the bespoke path: record and
                    # keep building the rest of the gang
                    results.failed[machine.name] = f"{type(exc).__name__}: {exc}"
                    logger.error(
                        "Machine %s: single build FAILED (%s); remaining "
                        "machines continue", machine.name, exc, exc_info=True,
                    )
                else:
                    counters["built"].labels("single").inc()
                if heartbeat is not None:
                    heartbeat.update(
                        phase="building", built=len(results),
                        failed_members=len(results.failed),
                    )
            else:
                fleet_groups.setdefault(_group_key(ae_kwargs), []).append(
                    (machine, ae_kwargs)
                )

        def train_group(group):
            # per-group isolation with bounded retry: a poisoned hparam
            # group (bad LR diverging the whole stack, an injected fault,
            # an OOM at this bucket's batch shape) exhausts its retries,
            # records its members as failed, and the remaining groups
            # still ship their artifacts
            for attempt in range(group_retries + 1):
                try:
                    # use_trace: the fleet trainer's bucket loop reads the
                    # current trace from the contextvar (parallel/fleet.py)
                    # instead of threading a parameter six layers down
                    with use_trace(trace):
                        _build_fleet_group(
                            group, output_dir, model_register_dir,
                            replace_cache, results,
                            checkpoint_dir=checkpoint_dir,
                            checkpoint_every=checkpoint_every,
                            mesh=trainer_mesh,
                            heartbeat=heartbeat, counters=counters,
                        )
                    return
                except Exception as exc:
                    if attempt < group_retries:
                        results.group_retries += 1
                        logger.warning(
                            "Fleet group of %d member(s) failed (attempt "
                            "%d/%d): %s; retrying",
                            len(group), attempt + 1, group_retries + 1, exc,
                        )
                        continue
                    error = f"{type(exc).__name__}: {exc}"
                    for m, _kw in group:
                        # members already shipped (cache hits, a pre-crash
                        # infeasible-CV single build) are built, not failed
                        if m.name not in results:
                            results.failed[m.name] = error
                    logger.error(
                        "Fleet group of %d member(s) FAILED after %d "
                        "attempt(s); members recorded in the partial "
                        "manifest; remaining groups continue: %s",
                        len(group), group_retries + 1, error, exc_info=True,
                    )

        # cross-arch gang scheduling: LARGE groups saturate the device on
        # their own and train one at a time, but a tail of SMALL
        # heterogeneous groups (different archs -> different compiled
        # programs, no shared vmap possible) would otherwise issue one
        # tiny dispatch each with the device idle during every group's
        # host-side work (tracing, XLA compile, stacking, unstacking).
        # GORDO_GANG_WIDTH worker threads drive those groups concurrently:
        # JAX dispatch is thread-safe, device work interleaves in the
        # queue, and group A's compile overlaps group B's compute. Results
        # are per-member (distinct keys per group), heartbeat writes are
        # serialized below, and the fleet program cache takes its own lock.
        gang_width = resolve_gang_width(len(fleet_groups))
        serial = [
            g for g in fleet_groups.values() if len(g) > GANG_SMALL_MAX
        ]
        small = [
            g for g in fleet_groups.values() if len(g) <= GANG_SMALL_MAX
        ]
        results.gang_width = gang_width
        for group in serial:
            train_group(group)
        if gang_width > 1 and len(small) > 1:
            import concurrent.futures as _futures

            with _futures.ThreadPoolExecutor(
                max_workers=gang_width, thread_name_prefix="gordo-gang"
            ) as pool:
                for f in [pool.submit(train_group, g) for g in small]:
                    f.result()  # train_group never raises; surface bugs
        else:
            for group in small:
                train_group(group)
    except BaseException as exc:
        # only non-build failures (preemption signals, a broken state
        # volume, bugs outside the isolated paths) land here now
        if heartbeat is not None:
            heartbeat.finish(
                "failed", built=len(results), error=f"{type(exc).__name__}: {exc}"
            )
        _finish_build_trace(trace, output_dir, error=True)
        raise
    _finish_build_trace(
        trace, output_dir,
        n_built=len(results), n_failed=len(results.failed),
    )
    if heartbeat is not None:
        if not results.failed:
            heartbeat.finish("done", built=len(results))
        elif results:
            heartbeat.finish(
                "partial", built=len(results),
                failed_members=len(results.failed),
                error=next(iter(results.failed.values())),
            )
        else:
            heartbeat.finish(
                "failed", built=0, failed_members=len(results.failed),
                error=next(iter(results.failed.values())),
            )
    return results


def _build_fleet_group(
    group: List[Tuple[Machine, Dict[str, Any]]],
    output_dir: str,
    model_register_dir: Optional[str],
    replace_cache: bool,
    results: Dict[str, str],
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 1,
    mesh=None,
    heartbeat=None,
    counters=None,
) -> None:
    _FP_GROUP.fire()
    ae_kwargs = copy.deepcopy(group[0][1])
    if counters is None:  # direct callers (tests) outside build_fleet
        counters = _build_counters()

    # cache check per machine first — reruns skip already-built members
    # (a CV-requesting machine only hits if the artifact records matching
    # per-fold scores, mirroring provide_saved_model)
    pending: List[Machine] = []
    pending_kwargs: Dict[str, Dict[str, Any]] = {}
    for machine, kw in group:
        key = calculate_model_key(machine.name, machine.model, machine.dataset, machine.metadata)
        if model_register_dir and not replace_cache:
            cached = os.path.join(model_register_dir, key)
            if (
                os.path.isdir(cached)
                and os.path.exists(os.path.join(cached, "model.pkl"))
                and _cache_satisfies_cv(cached, machine)
            ):
                logger.info("Machine %s: cache hit", machine.name)
                _mirror_artifact(cached, os.path.join(output_dir, machine.name))
                results[machine.name] = cached
                counters["cache_hits"].inc()
                continue
        pending.append(machine)
        pending_kwargs[machine.name] = kw
    if not pending:
        return

    # per-member vector knobs (LR/ES patience) for FleetTrainer.fit —
    # PENDING machines only: cache-hit members never reach the trainer,
    # and fit() rejects hparams for members it wasn't given
    member_hparams = {
        m.name: _member_hparams_of(pending_kwargs[m.name]) for m in pending
    }

    # host-side data loading (the IO hot loop, SURVEY.md §3.1). One process
    # feeds the whole gang here (SURVEY.md §7 hard part 2); stage_members
    # owns worker count and thread-vs-process engine selection
    # (utils/staging.py) so builds and the bench measure the same path.
    if heartbeat is not None:
        heartbeat.update(phase="loading", group_members=len(pending))
    t0 = time.time()
    loaded = stage_members([dict(m.dataset) for m in pending])
    member_data: Dict[str, np.ndarray] = {}
    datasets_meta: Dict[str, Dict] = {}
    for machine, (X, meta) in zip(pending, loaded):
        member_data[machine.name] = X  # DataFrame: trainer keeps tag names
        datasets_meta[machine.name] = meta
    load_elapsed = time.time() - t0

    # CV fold plan (VERDICT r3 next #2): fold training slices join the gang
    # as extra stacked members — one wider vmap program instead of
    # n_splits extra builds per machine. Machines whose folds are
    # infeasible for this family fall back to the single-build path (their
    # staged data is dropped; the single path re-loads, a rare edge).
    cv_plan, fold_data, infeasible = _plan_cv_folds(
        pending, member_data, ae_kwargs
    )
    for machine in infeasible:
        logger.info(
            "Machine %s: CV folds infeasible for the gang, single-build path",
            machine.name,
        )
        pending = [m for m in pending if m.name != machine.name]
        member_data.pop(machine.name, None)
        datasets_meta.pop(machine.name, None)
        member_hparams.pop(machine.name, None)
        results[machine.name] = provide_saved_model(
            machine.name,
            machine.model,
            machine.dataset,
            machine.metadata,
            output_dir=os.path.join(output_dir, machine.name),
            model_register_dir=model_register_dir,
            replace_cache=replace_cache,
            evaluation_config=machine.evaluation or None,
        )
        counters["built"].labels("single").inc()
    if not pending:
        return

    trainer_kwargs = {
        k: ae_kwargs.pop(k) for k in _TRAINER_KEYS if k in ae_kwargs
    }
    epoch_cb = None
    if heartbeat is not None:

        def epoch_cb(info):
            heartbeat.update(
                phase="training",
                bucket=[int(info["n_features"]), int(info["padded_rows"])],
                epoch=int(info["epoch"]),
                n_active=int(info["n_active"]),
            )

    trainer = FleetTrainer(
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
        mesh=mesh, epoch_callback=epoch_cb, **trainer_kwargs, **ae_kwargs,
    )
    t1 = time.time()
    from gordo_components_tpu.utils.profiling import device_memory_stats, maybe_profile

    # CV fold members train with their machine's own hyperparameters
    for name, (splits, _Xv) in cv_plan.items():
        for fold in range(len(splits)):
            member_hparams[_cv_key(name, fold)] = member_hparams[name]

    with maybe_profile(f"fleet-gang-{len(pending)}m"):
        fleet_models = trainer.fit(
            {**member_data, **fold_data}, member_hparams=member_hparams
        )
    train_elapsed = time.time() - t1
    trainer.last_stats["device_memory"] = device_memory_stats()
    if fold_data:
        trainer.last_stats["cv_fold_members"] = len(fold_data)

    cv_meta_by_name = _score_cv_folds(cv_plan, fleet_models)

    by_name = {m.name: m for m in pending}
    for name, fm in fleet_models.items():
        if _CV_SEP in name:
            continue  # fold members exist only to produce CV scores
        machine = by_name[name]
        det = fm.to_estimator()
        key = calculate_model_key(machine.name, machine.model, machine.dataset, machine.metadata)
        metadata = {
            "name": name,
            "checked_at": metadata_timestamp(),
            "dataset": datasets_meta[name],
            "model": {
                "model_config": machine.model,
                "fleet_trained": True,
                "fleet_stats": trainer.last_stats,
                "data_query_duration_sec": load_elapsed / max(1, len(pending)),
                "model_training_duration_sec": train_elapsed / max(1, len(pending)),
                "history": fm.history,
                "model_builder_cache_key": key,
                "trained": True,
                # detector metadata (thresholds + their provenance —
                # "exact" vs the fleet's "histogram-8192" streaming
                # quantiles), same placement as the single-build path
                # (build_model.py)
                **det.get_metadata(),
            },
            "user-defined": machine.metadata,
        }
        if name in cv_meta_by_name:
            metadata["model"]["cross-validation"] = cv_meta_by_name[name]
        dest = (
            os.path.join(model_register_dir, key)
            if model_register_dir
            else os.path.join(output_dir, name)
        )
        serializer.dump(det, dest, metadata=metadata)
        mirror = os.path.join(output_dir, name)
        if os.path.abspath(mirror) != os.path.abspath(dest):
            serializer.dump(det, mirror, metadata=metadata)
        results[name] = dest
        counters["built"].labels("fleet").inc()
        logger.info("Machine %s: fleet-built -> %s", name, dest)
    if heartbeat is not None:
        heartbeat.update(phase="building", built=len(results))
