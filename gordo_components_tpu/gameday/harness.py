"""The game-day harness: a real multi-process mesh, broken on purpose.

:class:`GamedayMesh` boots N serving replicas as REAL subprocesses
(``gameday/replica.py`` child entry — the shape ``tools/mesh_demo.py``
measures) plus a live in-process watchman on a real TCP port, then the
scenario runners in this module inject the catalog's failures and
collect the evidence ``scenarios.py`` judges:

- process-level faults: SIGKILL + respawn (crash/restart, herd);
- transport-level faults: the new blackhole/refuse/reset kinds
  (``resilience/faults.py``), armed in-process for the watchman side
  (``watchman.probe``) and over the subprocess boundary via
  ``GORDO_FAULTS`` for the replica side (``server.connection``,
  ``engine.queue`` latency);
- data-level faults: correlated mean-shift drift through the streaming
  ingest plane.

Everything is judged through public surfaces only — the watchman
routing table, ``/slo`` rollup, fleet ``/events``, the replica drift
views and the bulk client's own counters — because that is what a real
operator (and the PR 16 incident correlator) would see.
"""

import asyncio
import json
import logging
import os
import random
import signal
import socket
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from gordo_components_tpu.gameday.scenarios import SCENARIOS

logger = logging.getLogger(__name__)

__all__ = [
    "GamedayMesh",
    "RUNNERS",
    "build_fleet_artifacts",
    "render_verdict_table",
    "run_gameday",
]

N_FEATURES = 8
GAMEDAY_SCHEMA = "gordo.gameday-run/v1"
# the mesh shapes in boot order: every scenario declares which one it
# needs, and run_gameday boots each shape at most once per run
SHAPE_ORDER = ("partitioned", "replicated", "qos", "push", "streaming")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def build_fleet_artifacts(
    root: str, n_members: int = 4, n_features: int = N_FEATURES
) -> List[str]:
    """Train a small anomaly fleet into ``root`` (one artifact dir per
    member) — the shared-volume deploy shape every replica boots from."""
    import numpy as np

    from gordo_components_tpu import serializer
    from gordo_components_tpu.models import (
        AutoEncoder,
        DiffBasedAnomalyDetector,
    )

    rng = np.random.RandomState(0)
    X = rng.rand(256, n_features).astype("float32")
    names = []
    for i in range(n_members):
        det = DiffBasedAnomalyDetector(
            base_estimator=AutoEncoder(epochs=1, batch_size=128)
        )
        det.fit(X + 0.01 * i)
        name = f"gd-{i}"
        serializer.dump(
            det, os.path.join(root, name), metadata={"name": name}
        )
        names.append(name)
    return names


def scoring_body(n_features: int = N_FEATURES, rows: int = 16, seed: int = 1):
    import numpy as np

    from gordo_components_tpu.utils.wire import pack_frames

    X = np.random.RandomState(seed).rand(rows, n_features).astype("float32")
    return pack_frames([("X", X)])


class GamedayMesh:
    """N server subprocesses over one artifact dir + a live watchman.

    ``replica_env`` maps replica index -> extra environment for THAT
    subprocess (per-replica fault injection: ``GORDO_FAULTS`` rides
    here); ``common_env`` applies to every replica. ``partitioned``
    boots the deterministic member partition (each replica owns a
    slice); off, every replica loads the full collection (the
    replicated shape hedging needs)."""

    def __init__(
        self,
        root: str,
        members: List[str],
        project: str = "gameday",
        n_replicas: int = 2,
        partitioned: bool = True,
        refresh_interval: float = 0.5,
        common_env: Optional[Dict[str, str]] = None,
        replica_env: Optional[Dict[int, Dict[str, str]]] = None,
    ):
        self.root = root
        self.members = list(members)
        self.project = project
        self.n_replicas = int(n_replicas)
        self.partitioned = bool(partitioned)
        self.refresh_interval = float(refresh_interval)
        self.common_env = dict(common_env or {})
        self.replica_env = {
            int(k): dict(v) for k, v in (replica_env or {}).items()
        }
        self.ports: List[int] = []
        self.procs: List[Optional[subprocess.Popen]] = []
        self.base_urls: List[str] = []
        self.wm_url: Optional[str] = None
        self._wm_runner = None
        self.session = None  # shared aiohttp session (control plane)

    # ------------------------------ lifecycle ----------------------- #

    def _child_env(self, index: int) -> Dict[str, str]:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("GORDO_SERVER_WARMUP", "0")
        for key in ("GORDO_MESH_REPLICA_ID", "GORDO_MESH_REPLICAS",
                    "GORDO_FAULTS"):
            env.pop(key, None)
        if self.partitioned and self.n_replicas > 1:
            env["GORDO_MESH_REPLICA_ID"] = str(index)
            env["GORDO_MESH_REPLICAS"] = str(self.n_replicas)
        env.update(self.common_env)
        env.update(self.replica_env.get(index, {}))
        return env

    def _spawn(self, index: int) -> subprocess.Popen:
        return subprocess.Popen(
            [
                sys.executable, "-m",
                "gordo_components_tpu.gameday.replica",
                "--root", self.root, "--port", str(self.ports[index]),
            ],
            env=self._child_env(index),
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    async def wait_ready(self, index: int, timeout: float = 240.0) -> None:
        import aiohttp

        url = (
            f"{self.base_urls[index]}/gordo/v0/{self.project}/ready"
        )
        deadline = time.monotonic() + timeout
        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=3)
        ) as session:
            while time.monotonic() < deadline:
                try:
                    async with session.get(url) as resp:
                        if resp.status == 200:
                            return
                except asyncio.CancelledError:
                    raise
                except Exception:
                    pass
                await asyncio.sleep(0.25)
        raise RuntimeError(
            f"replica {index} (port {self.ports[index]}) never became ready"
        )

    async def start(self) -> "GamedayMesh":
        import aiohttp
        from aiohttp import web

        from gordo_components_tpu.watchman.server import build_watchman_app

        self.ports = [free_port() for _ in range(self.n_replicas)]
        self.base_urls = [f"http://127.0.0.1:{p}" for p in self.ports]
        self.procs = [self._spawn(i) for i in range(self.n_replicas)]
        await asyncio.gather(
            *(self.wait_ready(i) for i in range(self.n_replicas))
        )
        wm_app = build_watchman_app(
            self.project,
            self.base_urls[0],
            refresh_interval=self.refresh_interval,
            metrics_urls=[
                b + f"/gordo/v0/{self.project}/metrics"
                for b in self.base_urls
            ],
        )
        self._wm_runner = web.AppRunner(wm_app)
        await self._wm_runner.setup()
        wm_port = free_port()
        site = web.TCPSite(self._wm_runner, "127.0.0.1", wm_port)
        await site.start()
        self.wm_url = f"http://127.0.0.1:{wm_port}"
        self.session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=30)
        )
        # prime the routing table so every scenario starts from an
        # observed, versioned fleet
        await self.routing(refresh=True)
        return self

    async def stop(self) -> None:
        if self.session is not None:
            await self.session.close()
            self.session = None
        if self._wm_runner is not None:
            await self._wm_runner.cleanup()
            self._wm_runner = None
        for proc in self.procs:
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in self.procs:
            if proc is None:
                continue
            try:
                proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()

    async def __aenter__(self) -> "GamedayMesh":
        try:
            return await self.start()
        except BaseException:
            await self.stop()
            raise

    async def __aexit__(self, *exc_info) -> None:
        await self.stop()

    # ------------------------- process faults ----------------------- #

    def kill_replica(self, index: int, sig: int = signal.SIGKILL) -> None:
        proc = self.procs[index]
        if proc is not None and proc.poll() is None:
            proc.send_signal(sig)
            proc.wait(timeout=20)

    async def respawn_replica(self, index: int) -> None:
        self.procs[index] = self._spawn(index)
        await self.wait_ready(index)

    # ------------------------ observability taps -------------------- #

    async def wm_json(self, path: str, params=None) -> Any:
        async with self.session.get(self.wm_url + path, params=params) as r:
            return await r.json()

    async def routing(self, refresh: bool = False) -> Dict[str, Any]:
        return await self.wm_json(
            "/routing", params={"refresh": "1"} if refresh else None
        )

    async def events_since(self, wall: float) -> List[Dict[str, Any]]:
        body = await self.wm_json("/events", params={"limit": "500"})
        return [
            e for e in body.get("events", [])
            if isinstance(e, dict) and float(e.get("wall") or 0) >= wall
        ]

    async def wait_until(
        self,
        predicate: Callable[[Dict[str, Any]], bool],
        timeout: float = 30.0,
        interval: float = 0.4,
        refresh: bool = True,
    ) -> Optional[float]:
        """Poll the routing table until ``predicate(table)``; returns
        elapsed seconds, or None on timeout (the caller's 'detected'
        flag — a drill that times out fails its bound, not the run)."""
        t0 = time.monotonic()
        deadline = t0 + timeout
        while time.monotonic() < deadline:
            try:
                table = await self.routing(refresh=refresh)
                if table and predicate(table):
                    return time.monotonic() - t0
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            await asyncio.sleep(interval)
        return None

    def score_url(self, base: str, member: str) -> str:
        return (
            f"{base}/gordo/v0/{self.project}/{member}/anomaly/prediction"
        )

    async def ingest(
        self, base: str, member: str, rows, timestamps
    ) -> int:
        url = f"{base}/gordo/v0/{self.project}/{member}/ingest"
        async with self.session.post(
            url, json={"rows": rows, "timestamps": timestamps}
        ) as resp:
            await resp.read()
            return resp.status


def _replica_entry(table: Dict[str, Any], index: int) -> Dict[str, Any]:
    for rep in table.get("replicas", []):
        if rep.get("replica") == index:
            return rep
    return {}


class LoadLoop:
    """Sustained scoring load against the mesh, the way a
    partition-aware client behaves: every round consults the (live or
    frozen) routing table, posts each member's tensor body to its
    owner, and SKIPS members whose owner the table marks unreachable —
    that skip IS the containment the crash scenario judges.

    ``excused_replica`` marks one replica index whose failures are the
    scenario's declared blast radius (the replica being killed);
    failures anywhere else count against the verdict's ``non_200``."""

    def __init__(
        self,
        mesh: GamedayMesh,
        members: List[str],
        interval_s: float = 0.08,
        follow_routing: bool = True,
        rows: int = 16,
    ):
        self.mesh = mesh
        self.members = list(members)
        self.interval_s = float(interval_s)
        self.follow_routing = bool(follow_routing)
        self.body = scoring_body(rows=rows)
        self.statuses: Dict[str, int] = {}
        self.non_200 = 0
        self.excused = 0
        self.skipped = 0
        self.requests = 0
        self.excused_replica: Optional[int] = None
        self._stop = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._frozen: Optional[Dict[str, Any]] = None

    async def _round(self, session, table: Dict[str, Any]) -> None:
        from gordo_components_tpu.utils.wire import TENSOR_CONTENT_TYPE

        members_map = table.get("members", {})
        replicas = {
            r.get("replica"): r for r in table.get("replicas", [])
        }
        for member in self.members:
            owner_idx = members_map.get(member)
            owner = replicas.get(owner_idx)
            if owner is None or not owner.get("reachable"):
                self.skipped += 1
                continue
            status = 599  # transport failure pseudo-status
            try:
                async with session.post(
                    self.mesh.score_url(owner["url"], member),
                    data=self.body,
                    headers={"Content-Type": TENSOR_CONTENT_TYPE},
                ) as resp:
                    await resp.read()
                    status = resp.status
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            self.requests += 1
            key = str(status)
            self.statuses[key] = self.statuses.get(key, 0) + 1
            if status != 200:
                if owner_idx == self.excused_replica:
                    self.excused += 1
                else:
                    self.non_200 += 1

    async def _run(self) -> None:
        import aiohttp

        async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=15)
        ) as session:
            if not self.follow_routing:
                self._frozen = await self.mesh.routing()
            while not self._stop.is_set():
                table = (
                    self._frozen
                    if self._frozen is not None
                    else await self.mesh.routing()
                )
                await self._round(session, table)
                await asyncio.sleep(self.interval_s)

    def start(self) -> "LoadLoop":
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self

    async def stop(self) -> None:
        self._stop.set()
        if self._task is not None:
            await self._task


def _fallback_dataset() -> Dict[str, Any]:
    return {
        "type": "RandomDataset",
        "tag_list": [f"t-{j}" for j in range(N_FEATURES)],
        "resolution": "1min",
    }


# --------------------------------------------------------------------- #
# scenario runners: inject -> detect -> contain -> recover -> evidence
# --------------------------------------------------------------------- #


async def _run_replica_crash(mesh: GamedayMesh) -> Dict[str, Any]:
    victim = mesh.n_replicas - 1
    table0 = await mesh.routing(refresh=True)
    v0 = table0["version"]
    loop = LoadLoop(mesh, mesh.members).start()
    await asyncio.sleep(1.0)  # healthy-baseline rounds
    wall_kill = time.time()
    loop.excused_replica = victim
    mesh.kill_replica(victim, signal.SIGKILL)
    detection = await mesh.wait_until(
        lambda t: not _replica_entry(t, victim).get("reachable", True),
        timeout=45.0,
    )
    # a few contained rounds: the table now marks the corpse, so the
    # loop must be skipping its members and everything else stays 200
    await asyncio.sleep(1.0)
    t_respawn = time.monotonic()
    await mesh.respawn_replica(victim)
    healed = await mesh.wait_until(
        lambda t: (
            _replica_entry(t, victim).get("reachable")
            and set(t.get("members", {})) == set(mesh.members)
        ),
        timeout=60.0,
    )
    recovery_s = (
        time.monotonic() - t_respawn if healed is not None else None
    )
    loop.excused_replica = None
    await asyncio.sleep(0.8)  # post-recovery rounds, all replicas live
    await loop.stop()
    table1 = await mesh.routing(refresh=True)
    events = await mesh.events_since(wall_kill - 1.0)
    return {
        "injected": f"SIGKILL replica {victim} under load",
        "detected": detection is not None,
        "detection_latency_s": detection,
        "detection_signal": "routing table reachable=false + version step",
        "non_200": loop.non_200,
        "excused_non200": loop.excused,
        "skipped_while_dark": loop.skipped,
        "requests": loop.requests,
        "statuses": loop.statuses,
        "recovered": healed is not None,
        "recovery_s": recovery_s,
        "routing_version_steps": table1["version"] - v0,
        "events": events,
    }


async def _run_watchman_partition(mesh: GamedayMesh) -> Dict[str, Any]:
    from gordo_components_tpu import resilience

    table0 = await mesh.routing(refresh=True)
    v0 = table0["version"]
    # frozen table: the data plane keeps posting to the last-good
    # owners for the whole partition — watchman being dark to the fleet
    # must not take down scoring
    loop = LoadLoop(mesh, mesh.members, follow_routing=False).start()
    await asyncio.sleep(0.8)
    wall_cut = time.time()
    # the new transport-level fault kind: every watchman->replica probe
    # is refused, exactly what a network partition looks like from here
    resilience.configure_from_env("watchman.probe=refuse")
    try:
        detection = await mesh.wait_until(
            lambda t: all(
                not r.get("reachable") for r in t.get("replicas", [])
            ),
            timeout=30.0,
        )
    finally:
        resilience.disarm("watchman.probe")
    t_heal = time.monotonic()
    healed = await mesh.wait_until(
        lambda t: all(
            r.get("reachable") for r in t.get("replicas", [])
        )
        and set(t.get("members", {})) == set(mesh.members),
        timeout=30.0,
    )
    recovery_s = time.monotonic() - t_heal if healed is not None else None
    await asyncio.sleep(0.5)
    await loop.stop()
    table1 = await mesh.routing(refresh=True)
    events = await mesh.events_since(wall_cut - 1.0)
    return {
        "injected": "watchman.probe=refuse (watchman<->fleet partition)",
        "detected": detection is not None,
        "detection_latency_s": detection,
        "detection_signal": "all replicas unreachable in the table",
        "non_200": loop.non_200,
        "requests": loop.requests,
        "statuses": loop.statuses,
        "recovered": healed is not None,
        "recovery_s": recovery_s,
        "routing_version_steps": table1["version"] - v0,
        "events": events,
    }


async def _run_migration_storm(mesh: GamedayMesh) -> Dict[str, Any]:
    import pandas as pd

    from gordo_components_tpu.client import Client

    table0 = await mesh.routing(refresh=True)
    v0 = table0["version"]
    client = Client(
        mesh.project,
        base_url=mesh.base_urls[0],
        routing_url=mesh.wm_url,
        metadata_fallback_dataset=_fallback_dataset(),
        batch_size=40,
        parallelism=4,
        # shorter than the inter-round gap below: each round's stale-404
        # is ENTITLED to one forced refresh; a window longer than the
        # storm cadence would throttle recovery itself (the refresh
        # limiter's own behavior is pinned in tests/test_mesh.py)
        routing_refresh_window_s=1.0,
    )
    start = pd.Timestamp("2020-01-01T00:00:00Z")
    end = start + pd.Timedelta(minutes=80)
    errors: List[str] = []
    moves = 0
    # the storm: the same member migrates back and forth, DIRECTLY on
    # the replicas (acquire/release) — watchman's cached table (pinned
    # by the long refresh interval) goes stale each round, so every
    # round the client must detect it via a routed 404, force ONE
    # refresh, and re-post the failed chunks
    victim = sorted(mesh.members)[0]
    for _ in range(3):
        table = await mesh.routing(refresh=True)
        src = table["members"][victim]
        dst = (src + 1) % mesh.n_replicas
        async with mesh.session.post(
            f"{mesh.base_urls[dst]}/gordo/v0/{mesh.project}/mesh/acquire",
            json={"member": victim, "source": mesh.base_urls[src]},
        ) as resp:
            assert resp.status == 200, await resp.text()
        async with mesh.session.post(
            f"{mesh.base_urls[src]}/gordo/v0/{mesh.project}/mesh/release",
            json={"member": victim},
        ) as resp:
            assert resp.status == 200, await resp.text()
        moves += 1
        results = await client.predict_async(start, end)
        for res in results:
            if not res.ok:
                errors.extend(res.error_messages)
        # let the per-member forced-refresh window lapse before the next
        # round moves the member again
        await asyncio.sleep(1.2)
    table1 = await mesh.routing(refresh=True)
    stats = dict(client._fanout_stats)
    return {
        "injected": f"{moves} direct migrations of {victim!r} behind a "
        "stale watchman cache",
        "detected": stats["reroutes"] > 0,
        "detection_signal": "routed 404 -> forced refresh -> re-post",
        "non_200": len(errors),
        "statuses": {"errors": errors[:5]},
        "reroutes": stats["reroutes"],
        "routing_refreshes": stats["routing_refreshes"],
        "refreshes_throttled": stats["refreshes_throttled"],
        "routed_chunks": stats["routed_chunks"],
        "routing_version_steps": table1["version"] - v0,
        "recovered": True,
        "recovery_s": 0.0,
        "moves": moves,
    }


def _fast_window_burn(slo_body: Dict[str, Any]) -> float:
    """Worst burn over the FAST window ("30s" in the gray-failure mesh)
    across objectives — the decay signal recovery waits on (the slow
    window keeps remembering bad samples for minutes by design)."""
    worst = 0.0
    for obj in slo_body.get("objectives") or []:
        win = (obj.get("windows") or {}).get("30s")
        if win and win.get("burn_rate") is not None:
            worst = max(worst, float(win["burn_rate"]))
    return worst


async def _run_gray_failure(mesh: GamedayMesh) -> Dict[str, Any]:
    import pandas as pd

    from gordo_components_tpu.client import Client

    sick = mesh.n_replicas - 1
    client = Client(
        mesh.project,
        base_url=mesh.base_urls[0],
        routing_url=mesh.wm_url,
        metadata_fallback_dataset=_fallback_dataset(),
        batch_size=40,
        parallelism=4,
        hedge=True,
        replica_urls=list(mesh.base_urls),
        hedge_delay_init_s=0.1,
    )
    start = pd.Timestamp("2020-01-01T00:00:00Z")
    end = start + pd.Timedelta(minutes=80)
    errors: List[str] = []
    burn_peak = 0.0
    detection = None
    gray_status = None
    t0 = time.monotonic()
    # containment phase: the hedged client races the sick replica while
    # the fault is live — its wins are the proof traffic routed around
    # the slowness (hedged-away requests get cancelled, so this phase
    # alone cannot be trusted to land latency samples on the replica)
    for _ in range(4):
        results = await client.predict_async(start, end)
        for res in results:
            if not res.ok:
                errors.extend(res.error_messages)
        if client._hedge_stats["hedge_wins"] >= 3:
            break
    # detection phase: a DIRECT (unhedged) load loop — callers that
    # don't hedge ride out the full injected latency, their completions
    # land in the sick replica's latency histogram, and the watchman
    # /slo rollup must attribute the burn to it. The gray replica's own
    # health stays "ok" throughout — that is what makes it gray.
    loop = LoadLoop(
        mesh, mesh.members, follow_routing=False, interval_s=0.15
    ).start()
    deadline = time.monotonic() + 40.0
    while time.monotonic() < deadline:
        slo = await mesh.wm_json("/slo", params={"refresh": "1"})
        worst = slo.get("worst_burn") or {}
        if worst.get("burn_rate") is not None:
            burn_peak = max(burn_peak, float(worst["burn_rate"]))
        if (
            worst.get("replica") == sick
            and float(worst.get("burn_rate") or 0.0) >= 1.0
        ):
            detection = time.monotonic() - t0
            table = await mesh.routing(refresh=True)
            gray_status = _replica_entry(table, sick).get("status")
            break
        await asyncio.sleep(0.5)
    await loop.stop()
    if loop.non_200:
        errors.append(f"direct load saw {loop.non_200} non-200s")
    # recovery phase: the injected fault has a finite budget
    # (GORDO_FAULTS times=N rides the sick replica's env) — keep light
    # healthy load flowing until it is exhausted and the fast-window
    # burn decays below alerting
    recovered = False
    recovery_s = None
    t_rec = time.monotonic()
    for _ in range(60):
        results = await client.predict_async(start, end)
        for res in results:
            if not res.ok:
                errors.extend(res.error_messages)
        slo = await mesh.wm_json("/slo", params={"refresh": "1"})
        worst = slo.get("worst_burn") or {}
        burn_peak = max(burn_peak, float(worst.get("burn_rate") or 0.0))
        if _fast_window_burn(slo) < 1.0:
            recovered = True
            recovery_s = time.monotonic() - t_rec
            break
        await asyncio.sleep(1.5)
    hedge_stats = dict(client._hedge_stats)
    return {
        "injected": f"engine.queue latency fault on replica {sick} "
        "(alive, healthz ok, slow)",
        "detected": detection is not None,
        "detection_latency_s": detection,
        "detection_signal": "watchman /slo worst_burn attributed to the "
        "sick replica",
        "gray_replica_status": gray_status,
        "non_200": len(errors),
        "statuses": {"errors": errors[:5]},
        "hedges": hedge_stats.get("hedges", 0),
        "hedge_wins": hedge_stats.get("hedge_wins", 0),
        "burn_peak": burn_peak,
        "recovered": recovered,
        "recovery_s": recovery_s,
    }


async def _run_tenant_noisy_neighbor(mesh: GamedayMesh) -> Dict[str, Any]:
    """best_effort flood vs steady interactive probes on the qos mesh.

    Phases: (1) unloaded interactive baseline -> p99; (2) flood: N
    concurrent best_effort workers per replica (tenant ``flood``, rate-
    limited by GORDO_QOS_TENANTS and depth-limited by the per-class
    shed fractions) while the SAME interactive probe keeps scoring and
    the watchman per-class rollup is polled for the flood class's burn;
    (3) evidence: per-replica GET /qos sheds -> precision, probe
    latencies -> p99 ratio, probe statuses -> non_200."""
    import aiohttp

    from gordo_components_tpu.utils.wire import TENSOR_CONTENT_TYPE

    member = mesh.members[0]
    body = scoring_body(rows=16)
    flood_body = scoring_body(rows=32, seed=2)
    flood_headers = {
        "Content-Type": TENSOR_CONTENT_TYPE,
        "X-Gordo-Tenant": "flood",
        "X-Gordo-Priority": "best_effort",
    }
    probe_headers = {"Content-Type": TENSOR_CONTENT_TYPE}

    async def probe_once(session, base) -> Tuple[int, float]:
        t0 = time.monotonic()
        try:
            async with session.post(
                mesh.score_url(base, member), data=body,
                headers=probe_headers,
            ) as resp:
                await resp.read()
                return resp.status, time.monotonic() - t0
        except asyncio.CancelledError:
            raise
        except Exception:
            return 599, time.monotonic() - t0

    def p99(samples: List[float]) -> Optional[float]:
        if not samples:
            return None
        s = sorted(samples)
        return s[min(len(s) - 1, int(0.99 * len(s)))]

    flood_statuses: Dict[str, int] = {}
    stop = asyncio.Event()

    async def flood_worker(session, base) -> None:
        while not stop.is_set():
            try:
                async with session.post(
                    mesh.score_url(base, member), data=flood_body,
                    headers=flood_headers,
                ) as resp:
                    await resp.read()
                    key = str(resp.status)
            except asyncio.CancelledError:
                raise
            except Exception:
                key = "599"
            flood_statuses[key] = flood_statuses.get(key, 0) + 1

    timeout = aiohttp.ClientTimeout(total=30)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        # -------- baseline: unloaded interactive p99 ------------------ #
        base_lat: List[float] = []
        errors: List[str] = []
        for i in range(40):
            status, dt = await probe_once(
                session, mesh.base_urls[i % mesh.n_replicas]
            )
            if status == 200:
                base_lat.append(dt)
            else:
                errors.append(f"baseline probe {status}")
        # -------- flood phase ----------------------------------------- #
        workers = [
            asyncio.get_running_loop().create_task(
                flood_worker(session, base)
            )
            for base in mesh.base_urls
            for _ in range(12)
        ]
        flood_lat: List[float] = []
        probe_statuses: Dict[str, int] = {}
        non_200 = 0
        class_burn_peak = None
        deadline = time.monotonic() + 15.0
        i = 0
        try:
            while time.monotonic() < deadline:
                status, dt = await probe_once(
                    session, mesh.base_urls[i % mesh.n_replicas]
                )
                i += 1
                probe_statuses[str(status)] = (
                    probe_statuses.get(str(status), 0) + 1
                )
                if status == 200:
                    flood_lat.append(dt)
                else:
                    non_200 += 1
                if i % 4 == 0:
                    slo = await mesh.wm_json(
                        "/slo", params={"refresh": "1"}
                    )
                    entry = (slo.get("classes") or {}).get(
                        "flood|best_effort"
                    )
                    for w in (entry or {}).get("windows", {}).values():
                        burn = w.get("burn_rate")
                        if burn is not None and (
                            class_burn_peak is None
                            or burn > class_burn_peak
                        ):
                            class_burn_peak = burn
        finally:
            stop.set()
            await asyncio.gather(*workers, return_exceptions=True)
        # -------- evidence: admission sheds per replica --------------- #
        shed_total = 0
        shed_flood_class = 0
        for base in mesh.base_urls:
            url = f"{base}/gordo/v0/{mesh.project}/qos"
            async with session.get(url) as resp:
                qos_doc = await resp.json()
            for key, n in (
                (qos_doc.get("admission") or {}).get("shed") or {}
            ).items():
                shed_total += n
                # key is "tenant|class|reason"
                if key.split("|")[1:2] == ["best_effort"]:
                    shed_flood_class += n
    p99_base = p99(base_lat)
    p99_flood = p99(flood_lat)
    ratio = (
        round(p99_flood / p99_base, 3)
        if p99_base and p99_flood
        else None
    )
    precision = (
        round(shed_flood_class / shed_total, 4) if shed_total else None
    )
    return {
        "injected": (
            f"best_effort flood (tenant=flood, {12 * mesh.n_replicas} "
            "workers) against a steady interactive probe"
        ),
        "detected": shed_total > 0,
        "detection_signal": "admission sheds on GET /qos + per-class "
        "burn on the watchman /slo rollup",
        "non_200": non_200 + len(errors),
        "statuses": {
            "interactive": probe_statuses,
            "flood": flood_statuses,
            "errors": errors[:5],
        },
        "interactive_p99_baseline_s": p99_base,
        "interactive_p99_flood_s": p99_flood,
        "interactive_p99_ratio": ratio,
        "interactive_requests": len(base_lat) + sum(
            probe_statuses.values()
        ),
        "flood_requests": sum(flood_statuses.values()),
        "shed_total": shed_total,
        "shed_on_flood_class": shed_flood_class,
        "shed_precision": precision,
        "class_burn_peak": class_burn_peak,
        # the flood is the declared blast radius; nothing to heal
        "recovered": True,
        "recovery_s": 0.0,
    }


async def _run_thundering_herd(mesh: GamedayMesh) -> Dict[str, Any]:
    import aiohttp

    from gordo_components_tpu.client.subscribe import PushSubscriber

    target = mesh.members[0]
    base = mesh.base_urls[0]
    n_subs = 6
    subs = [
        PushSubscriber(
            base,
            mesh.project,
            target,
            subscriber=f"herd-{i}",
            poll_timeout_s=2.0,
            reconnect_base_s=0.05,
            reconnect_cap_s=1.5,
            rng=random.Random(1000 + i),
        )
        for i in range(n_subs)
    ]
    stop = asyncio.Event()
    ingest_stop = asyncio.Event()

    async def feed() -> None:
        # steady ingest so polls have windows to deliver; tolerant of
        # the replica's injected connection resets and the restart
        t = 1_600_000_000.0
        rng = random.Random(7)
        while not ingest_stop.is_set():
            rows = [
                [rng.random() for _ in range(N_FEATURES)]
                for _ in range(16)
            ]
            ts = [t + i for i in range(16)]
            t += 16.0
            try:
                await mesh.ingest(base, target, rows, ts)
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            await asyncio.sleep(0.3)

    async with aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=10)
    ) as session:
        feeder = asyncio.get_running_loop().create_task(feed())
        tasks = [
            asyncio.get_running_loop().create_task(
                sub.run(session, stop=stop)
            )
            for sub in subs
        ]
        try:
            # all subscribers attached and polling through the flaky
            # transport (server.connection=reset rides GORDO_FAULTS)
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline and not all(
                s.stats["polls"] >= 1 for s in subs
            ):
                await asyncio.sleep(0.2)
            polls_at_kill = [s.stats["polls"] for s in subs]
            table0 = await mesh.routing(refresh=True)
            v0 = table0["version"]
            wall_kill = time.time()
            mesh.kill_replica(0, signal.SIGKILL)
            detection = await mesh.wait_until(
                lambda t: not _replica_entry(t, 0).get("reachable", True),
                timeout=45.0,
            )
            t_respawn = time.monotonic()
            await mesh.respawn_replica(0)
            await mesh.wait_until(
                lambda t: _replica_entry(t, 0).get("reachable"),
                timeout=60.0,
            )
            # recovery: every subscriber must long-poll SUCCESSFULLY
            # again (new ingests keep flowing from the feeder)
            deadline = time.monotonic() + 90.0
            while time.monotonic() < deadline and not all(
                s.stats["polls"] > p0
                for s, p0 in zip(subs, polls_at_kill)
            ):
                await asyncio.sleep(0.3)
            recovery_s = time.monotonic() - t_respawn
            lost = [
                s.subscriber
                for s, p0 in zip(subs, polls_at_kill)
                if s.stats["polls"] <= p0
            ]
        finally:
            stop.set()
            ingest_stop.set()
            for task in tasks:
                task.cancel()
            feeder.cancel()
            await asyncio.gather(*tasks, feeder, return_exceptions=True)
    table1 = await mesh.routing(refresh=True)
    events = await mesh.events_since(wall_kill - 1.0)
    delays = [d for s in subs for d in s.reconnect_delays]
    return {
        "injected": "SIGKILL the push replica under 6 long-poll "
        "subscribers + server.connection=reset transport flakiness",
        "detected": detection is not None,
        "detection_latency_s": detection,
        "detection_signal": "routing table reachable=false + "
        "mesh.replica_unreachable",
        "non_200": 0,
        "subscribers": n_subs,
        "subscribers_lost": lost,
        "reconnects": sum(s.stats["reconnects"] for s in subs),
        "poll_failures": sum(s.stats["failures"] for s in subs),
        "distinct_reconnect_delays": len(
            {round(d, 4) for d in delays}
        ),
        "reconnect_delay_span_s": (
            round(max(delays) - min(delays), 4) if delays else 0.0
        ),
        "recovered": not lost,
        "recovery_s": recovery_s,
        "routing_version_steps": table1["version"] - v0,
        "events": events,
    }


async def _run_correlated_drift(mesh: GamedayMesh) -> Dict[str, Any]:
    import numpy as np

    table = await mesh.routing(refresh=True)
    owners = table["members"]
    rep_urls = {r["replica"]: r["url"] for r in table["replicas"]}
    # one victim member per replica: the SAME upstream shift hits the
    # whole fleet at once — that correlation is what the rollup must see
    victims: Dict[int, str] = {}
    for member in sorted(mesh.members):
        idx = owners.get(member)
        if idx is not None and idx not in victims:
            victims[idx] = member
    assert len(victims) >= 2, f"need members on 2+ replicas: {owners}"
    rng = np.random.RandomState(3)
    t_base = 1_600_000_000.0

    async def ingest_rows(idx: int, member: str, shift: float, t0: float,
                          n: int) -> None:
        rows = (rng.rand(n, N_FEATURES) + shift).tolist()
        ts = [t0 + i for i in range(n)]
        status = await mesh.ingest(rep_urls[idx], member, rows, ts)
        assert status == 200, (member, status)

    # healthy windows everywhere -> nothing drifts
    for idx, member in victims.items():
        await ingest_rows(idx, member, 0.0, t_base, 96)

    async def drift_view(idx: int) -> Dict[str, Any]:
        url = (
            f"{rep_urls[idx]}/gordo/v0/{mesh.project}/drift"
        )
        async with mesh.session.get(url, params={"refresh": "1"}) as r:
            return await r.json()

    for idx in victims:
        body = await drift_view(idx)
        assert body.get("drifted") == [], body.get("drifted")

    loop = LoadLoop(
        mesh, list(victims.values()), follow_routing=False
    ).start()
    wall_shift = time.time()
    t0 = time.monotonic()
    for idx, member in victims.items():
        await ingest_rows(idx, member, 3.0, t_base + 200.0, 192)
    # detection: every replica's own detector must flag its member
    drifted_replicas: List[int] = []
    detection = None
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline and len(drifted_replicas) < len(
        victims
    ):
        for idx, member in victims.items():
            if idx in drifted_replicas:
                continue
            body = await drift_view(idx)
            if member in (body.get("drifted") or []):
                drifted_replicas.append(idx)
        if len(drifted_replicas) == len(victims):
            detection = time.monotonic() - t0
        else:
            await asyncio.sleep(0.5)
    # the fleet rollup unions the attribution
    rollup = await mesh.wm_json("/drift", params={"refresh": "1"})
    rollup_drifted = sorted(rollup.get("drifted") or [])
    # recovery: recalibrate the flagged members on each replica and
    # wait for the flags to clear
    t_rec = time.monotonic()
    for idx, member in victims.items():
        url = f"{rep_urls[idx]}/gordo/v0/{mesh.project}/adapt"
        async with mesh.session.post(
            url, json={"mode": "recalibrate", "targets": [member]}
        ) as resp:
            body = await resp.json()
            assert resp.status == 200 and body.get("applied"), body
    recovered = False
    recovery_s = None
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        still = []
        for idx, member in victims.items():
            body = await drift_view(idx)
            if member in (body.get("drifted") or []):
                still.append(member)
        if not still:
            recovered = True
            recovery_s = time.monotonic() - t_rec
            break
        await asyncio.sleep(0.5)
    await loop.stop()
    events = await mesh.events_since(wall_shift - 1.0)
    return {
        "injected": f"mean-shift drift on {sorted(victims.values())} "
        "(one member per replica, same instant)",
        "detected": detection is not None,
        "detection_latency_s": detection,
        "detection_signal": "per-replica drift sweeps + fleet /drift "
        "rollup union",
        "non_200": loop.non_200,
        "requests": loop.requests,
        "statuses": loop.statuses,
        "drifted_replicas": sorted(drifted_replicas),
        "rollup_drifted": rollup_drifted,
        "recovered": recovered,
        "recovery_s": recovery_s,
        "events": events,
    }


RUNNERS: Dict[str, Callable[[GamedayMesh], Any]] = {
    "replica_crash_restart": _run_replica_crash,
    "watchman_partition": _run_watchman_partition,
    "migration_storm": _run_migration_storm,
    "gray_failure_slow_replica": _run_gray_failure,
    "tenant_noisy_neighbor": _run_tenant_noisy_neighbor,
    "thundering_herd": _run_thundering_herd,
    "correlated_drift": _run_correlated_drift,
}


# --------------------------------------------------------------------- #
# the run loop: one mesh boot per shape, scenarios in catalog order
# --------------------------------------------------------------------- #


def _mesh_for(shape: str, root: str, members: List[str]) -> GamedayMesh:
    if shape == "partitioned":
        # the LONG refresh interval is deliberate: the migration-storm
        # drill needs watchman's cached table to go genuinely stale;
        # detection polls force rebuilds explicitly
        return GamedayMesh(
            root, members, n_replicas=2, partitioned=True,
            refresh_interval=300.0,
        )
    if shape == "replicated":
        return GamedayMesh(
            root, members, n_replicas=2, partitioned=False,
            refresh_interval=0.5,
            common_env={
                "GORDO_SLO_SAMPLE_S": "0.2",
                "GORDO_SLO_WINDOWS": "30s,5m",
                "GORDO_SLO_OBJECTIVES": json.dumps([
                    {"name": "availability", "target": 0.999},
                    {"name": "p95_latency_ms", "target": 120.0},
                ]),
            },
            replica_env={
                1: {"GORDO_FAULTS": "engine.queue=latency:0.25,times=60"},
            },
        )
    if shape == "qos":
        # the noisy-neighbor drill: clean replicas (no armed faults — a
        # latency fault would pollute the p99 baseline), a tight engine
        # queue so a flood reaches the per-class shed thresholds within
        # seconds, fast per-class SLO windows, and a named flood tenant
        # so its metric label survives the cardinality bound
        return GamedayMesh(
            root, members, n_replicas=2, partitioned=False,
            refresh_interval=0.5,
            common_env={
                "GORDO_SLO_SAMPLE_S": "0.2",
                "GORDO_SLO_WINDOWS": "30s,5m",
                "GORDO_SLO_OBJECTIVES": json.dumps([
                    {"name": "availability", "target": 0.999},
                ]),
                "GORDO_BANK_MAX_QUEUE": "32",
                "GORDO_QOS_TENANTS": json.dumps(
                    {"flood": {"rate": 60.0, "burst": 90.0}}
                ),
            },
        )
    if shape == "push":
        return GamedayMesh(
            root, members, n_replicas=1, partitioned=False,
            refresh_interval=0.5,
            common_env={
                "GORDO_STREAM": "1",
                "GORDO_PUSH": "1",
                "GORDO_STREAM_MIN_ROWS": "8",
                "GORDO_FAULTS": "server.connection=reset,p=0.15,seed=11",
            },
        )
    if shape == "streaming":
        return GamedayMesh(
            root, members, n_replicas=2, partitioned=True,
            refresh_interval=0.5,
            common_env={
                "GORDO_STREAM": "1",
                "GORDO_STREAM_WINDOW": "128",
                "GORDO_STREAM_MIN_ROWS": "32",
                # manual adapt only: the drill drives recalibration
                # itself so recovery time is the drill's to measure
                "GORDO_STREAM_INTERVAL_S": "3600",
            },
        )
    raise ValueError(f"unknown mesh shape {shape!r}")


async def run_gameday(
    root: str,
    scenario_names: Optional[List[str]] = None,
    n_members: int = 4,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict[str, Any]:
    """Run the named scenarios (default: the full catalog), one mesh
    boot per required shape, and return the judged run document."""
    names = list(scenario_names or SCENARIOS)
    unknown = sorted(set(names) - set(SCENARIOS))
    if unknown:
        raise ValueError(
            f"unknown scenario(s) {unknown}; known: {sorted(SCENARIOS)}"
        )
    members = build_fleet_artifacts(root, n_members)
    single_core = (os.cpu_count() or 1) < 2
    say = progress or (lambda msg: None)
    doc: Dict[str, Any] = {
        "schema": GAMEDAY_SCHEMA,
        "cpu_count": os.cpu_count(),
        "single_core": single_core,
        "scenarios": {},
    }
    for shape in SHAPE_ORDER:
        todo = [n for n in names if SCENARIOS[n].mesh == shape]
        if not todo:
            continue
        say(f"booting {shape} mesh for {todo}")
        async with _mesh_for(shape, root, members) as mesh:
            for name in todo:
                scenario = SCENARIOS[name]
                say(f"scenario {name}: {scenario.description[:60]}...")
                t0 = time.monotonic()
                try:
                    evidence = await RUNNERS[name](mesh)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.exception("scenario %s crashed", name)
                    evidence = {
                        "error": f"{type(exc).__name__}: {exc}",
                        "detected": False,
                        "non_200": 0,
                    }
                    verdict = scenario.finalize(evidence, single_core)
                    verdict["failures"].insert(
                        0, f"scenario crashed: {evidence['error']}"
                    )
                    verdict["passed"] = False
                    verdict["wall_seconds"] = round(
                        time.monotonic() - t0, 3
                    )
                    doc["scenarios"][name] = verdict
                    continue
                verdict = scenario.finalize(evidence, single_core)
                verdict["wall_seconds"] = round(time.monotonic() - t0, 3)
                # the judged timeline is evidence, but the full event
                # dicts bloat the doc — keep the causal skeleton
                if "events" in verdict:
                    verdict["events"] = [
                        {
                            "type": e.get("type"),
                            "replica": e.get("replica"),
                            "wall": e.get("wall"),
                            "severity": e.get("severity"),
                        }
                        for e in verdict["events"]
                    ]
                doc["scenarios"][name] = verdict
                say(
                    f"scenario {name}: "
                    f"{'PASS' if verdict['passed'] else 'FAIL'}"
                )
    doc["passed"] = all(
        v.get("passed") for v in doc["scenarios"].values()
    ) and bool(doc["scenarios"])
    return doc


def render_verdict_table(doc: Dict[str, Any]) -> str:
    """The per-scenario verdict table the demo prints (and the docs'
    triage runbook references)."""
    rows = [
        (
            "scenario", "verdict", "detect(s)", "non200", "recover(s)",
            "notes",
        )
    ]
    for name, v in doc.get("scenarios", {}).items():
        det = v.get("detection_latency_s")
        rec = v.get("recovery_s")
        rows.append(
            (
                name,
                "PASS" if v.get("passed") else "FAIL",
                f"{det:.1f}" if isinstance(det, (int, float)) else "-",
                str(v.get("non_200", "-")),
                f"{rec:.1f}" if isinstance(rec, (int, float)) else "-",
                "; ".join(v.get("failures", []))[:60] or "ok",
            )
        )
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines = []
    for i, row in enumerate(rows):
        lines.append(
            "  ".join(cell.ljust(w) for cell, w in zip(row, widths))
        )
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)
