"""Pre-promotion game-day gates: bounded single-replica drills the
fleet compiler runs between canary and promote.

The full harness (``gameday/harness.py``) breaks a whole multi-process
mesh — minutes of wall time, its own fleet. A promotion decision needs
a cheaper question answered about THE canary replica that just served
its window: *would the failure modes this rollout can actually ship
survive a drill right now?* Each ``gate_capable`` scenario in the
catalog has a gate-mode drill here, run through public surfaces only:

- ``replica_crash_restart`` gate-mode: POST ``/reload`` (the same
  zero-downtime swap a crash recovery or promotion lands through)
  while probe traffic is in flight — the zero-non-200 swap invariant,
  judged from both the probes and the server's own error counter;
- ``gray_failure_slow_replica`` gate-mode: a probe window over the
  live replica, judged by its OWN ``/slo`` fast-burn state — a canary
  that answers but burns its latency budget is not a promotable
  canary;
- ``tenant_noisy_neighbor`` gate-mode: a best_effort-tagged scoring
  flood against the canary while interactive probes run, judged from
  ``GET /qos`` counter deltas — the flood must classify, sheds must
  land on it, and the probes must stay all-200.

Verdicts use the shared envelope (``replay/verdict.py``), so the fleet
report, BENCH_DETAIL and the full harness all read the same way. The
executor maps a failed gate to a failed step, which blocks promote via
the ordinary dependency propagation (``workflow/executor.py``).

Sync on purpose: the executor is a sync control-plane process
(requests-based), and the gate runs inside its step loop.
"""

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from gordo_components_tpu.gameday.scenarios import GATE_DEFAULT, SCENARIOS
from gordo_components_tpu.replay.verdict import finalize_verdict

logger = logging.getLogger(__name__)

__all__ = ["GATE_SCHEMA", "run_promotion_gate"]

GATE_SCHEMA = "gordo.gameday-gate/v1"

# latency-class objectives burn on slow hardware regardless of rollout
# quality — their fast burn only fails the gate on multi-core hosts
# (the single-core honesty rule); availability/goodput burns are
# structural and fail everywhere
_LATENCY_OBJECTIVE_PREFIX = "p"


class _Probe:
    """Background probe traffic during a drill: cheap control-plane
    GETs (``/healthz``, ``/models``) plus the caller's ``traffic``
    callable (real scoring load, e.g. the executor's traffic hook),
    with client-side status/latency accounting."""

    def __init__(
        self,
        base_url: str,
        project: str,
        traffic: Optional[Callable[[str], Any]] = None,
        interval_s: float = 0.05,
        http_timeout: float = 10.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.project = project
        self.traffic = traffic
        self.interval_s = interval_s
        self.http_timeout = http_timeout
        self.statuses: Dict[str, int] = {}
        self.latencies_s: List[float] = []
        self.traffic_errors = 0
        self.requests = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def non_200(self) -> int:
        return sum(
            n for code, n in self.statuses.items() if code != "200"
        )

    def _run(self) -> None:
        import requests

        urls = [
            f"{self.base_url}/gordo/v0/{self.project}/healthz",
            f"{self.base_url}/gordo/v0/{self.project}/models",
        ]
        i = 0
        while not self._stop.is_set():
            url = urls[i % len(urls)]
            i += 1
            t0 = time.monotonic()
            try:
                resp = requests.get(url, timeout=self.http_timeout)
                status = str(resp.status_code)
            except Exception:
                status = "599"  # transport failure pseudo-status
            self.requests += 1
            self.latencies_s.append(time.monotonic() - t0)
            self.statuses[status] = self.statuses.get(status, 0) + 1
            if self.traffic is not None:
                try:
                    self.traffic(self.base_url)
                except Exception:
                    # scoring failures during a drill are the server's
                    # to count (its error counter delta is judged); a
                    # hook crash here must not kill the probe thread
                    self.traffic_errors += 1
            self._stop.wait(self.interval_s)

    def __enter__(self) -> "_Probe":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def __exit__(self, *exc_info) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)

    def p95_ms(self) -> Optional[float]:
        if not self.latencies_s:
            return None
        ordered = sorted(self.latencies_s)
        idx = min(len(ordered) - 1, int(0.95 * len(ordered)))
        return round(ordered[idx] * 1000.0, 2)


class _GateContext:
    def __init__(
        self,
        base_url: str,
        project: str,
        traffic: Optional[Callable[[str], Any]],
        http_timeout: float,
        settle_s: float,
    ):
        self.base_url = base_url.rstrip("/")
        self.project = project
        self.traffic = traffic
        self.http_timeout = http_timeout
        self.settle_s = settle_s

    def _url(self, endpoint: str) -> str:
        return f"{self.base_url}/gordo/v0/{self.project}/{endpoint}"

    def get_json(self, endpoint: str) -> Dict[str, Any]:
        import requests

        resp = requests.get(self._url(endpoint), timeout=self.http_timeout)
        resp.raise_for_status()
        return resp.json()

    def post_json(self, endpoint: str) -> Dict[str, Any]:
        import requests

        resp = requests.post(self._url(endpoint), timeout=self.http_timeout)
        resp.raise_for_status()
        return resp.json()


def _gate_reload_under_load(ctx: _GateContext):
    """The crash/restart scenario's shippable failure mode: a
    generation swap that drops requests. Drill: probe + score while
    POST /reload lands the zero-downtime swap; every response must
    stay 200 and the server's own error counter must not move."""
    errors_before = int(ctx.get_json("stats").get("errors", 0))
    reload_error: Optional[str] = None
    swap: Any = None
    with _Probe(
        ctx.base_url, ctx.project, ctx.traffic,
        http_timeout=ctx.http_timeout,
    ) as probe:
        time.sleep(ctx.settle_s)  # pre-swap baseline probes
        try:
            body = ctx.post_json("reload")
            swap = body.get("swap", body)
        except Exception as exc:
            reload_error = f"{type(exc).__name__}: {exc}"
        time.sleep(ctx.settle_s)  # post-swap probes on the new bank
    errors_after = int(ctx.get_json("stats").get("errors", 0))
    server_error_delta = max(0, errors_after - errors_before)
    verdict: Dict[str, Any] = {
        "gate_mode": "reload_under_load",
        "injected": "POST /reload (zero-downtime swap) under probe load",
        "non_200": probe.non_200 + server_error_delta,
        "probe_requests": probe.requests,
        "probe_statuses": probe.statuses,
        "probe_p95_ms": probe.p95_ms(),
        "server_error_delta": server_error_delta,
        "swap": swap,
        "detected": reload_error is None,
    }
    fails: List[str] = []
    if reload_error is not None:
        fails.append(f"reload failed: {reload_error}")
    if verdict["non_200"]:
        fails.append(
            f"{verdict['non_200']} non-200(s) during the swap window "
            "(budget 0): the zero-downtime invariant broke "
            f"(probe statuses: {probe.statuses}, "
            f"server error delta: {server_error_delta})"
        )
    return verdict, fails


def _gate_latency_burn_probe(ctx: _GateContext):
    """The gray-failure scenario's shippable failure mode: a canary
    that answers 200 but is sick-slow. Drill: a probe window, then
    judge the replica by its OWN SLO surface — a fast-burning
    availability/goodput objective fails everywhere; a fast-burning
    latency objective fails on multi-core hosts (single-core machines
    are allowed to be slow, not allowed to be broken)."""
    from gordo_components_tpu.workflow.canary import slo_fast_burn

    with _Probe(
        ctx.base_url, ctx.project, ctx.traffic,
        http_timeout=ctx.http_timeout,
    ) as probe:
        time.sleep(max(ctx.settle_s * 2, 1.0))
    slo = ctx.get_json("slo?refresh=1")
    burning = slo_fast_burn(slo)
    single_core = (os.cpu_count() or 1) < 2
    verdict: Dict[str, Any] = {
        "gate_mode": "latency_burn_probe",
        "injected": "probe window over the live canary replica",
        "non_200": probe.non_200,
        "probe_requests": probe.requests,
        "probe_statuses": probe.statuses,
        "probe_p95_ms": probe.p95_ms(),
        "slo_enabled": bool(slo.get("enabled", True)),
        "fast_burning_objective": burning,
        "detected": True,
    }
    fails: List[str] = []
    if verdict["non_200"]:
        fails.append(
            f"{verdict['non_200']} non-200(s) during the probe window "
            f"(budget 0; statuses: {probe.statuses})"
        )
    if burning is not None:
        is_latency = burning.startswith(
            _LATENCY_OBJECTIVE_PREFIX
        ) and "latency" in burning
        if not is_latency:
            fails.append(
                f"objective {burning!r} is fast-burning on the canary "
                "replica"
            )
        elif not single_core:
            fails.append(
                f"latency objective {burning!r} is fast-burning on the "
                "canary replica (multi-core host: the canary is "
                "sick-slow, not promotable)"
            )
        else:
            verdict["latency_burn_waived"] = "single-core host"
    return verdict, fails


def _gate_qos_fairness(ctx: _GateContext):
    """The noisy-neighbor scenario's shippable failure mode: a rollout
    that breaks classification or admission, so a best_effort flood
    hurts interactive traffic — or the QoS surface itself vanished.
    Drill: flood the canary's scoring endpoint with best_effort-tagged
    requests (valid bodies, widths from GET /qos ``feature_widths``)
    while the probe + the caller's real traffic hook keep running;
    judge from the GET /qos counter DELTAS — the flood must classify
    as best_effort, any admission sheds must land on it, and the probe
    window must stay all-200."""
    import json as _json

    import requests

    qos0 = ctx.get_json("qos")
    widths = (qos0.get("engine") or {}).get("feature_widths") or {}
    fails: List[str] = []
    verdict: Dict[str, Any] = {
        "gate_mode": "qos_fairness_flood",
        "injected": "best_effort-tagged scoring flood against the "
        "canary while interactive probes run",
        "detected": bool(qos0.get("enabled")),
    }
    if not qos0.get("enabled") or not widths:
        fails.append(
            "GET /qos unavailable or no banked targets to flood "
            f"(enabled={qos0.get('enabled')}, widths={len(widths)})"
        )
        verdict["non_200"] = 0
        return verdict, fails
    target, width = sorted(widths.items())[0]
    flood_statuses: Dict[str, int] = {}
    stop = threading.Event()

    def flood() -> None:
        sess = requests.Session()
        url = (
            f"{ctx.base_url}/gordo/v0/{ctx.project}/{target}/prediction"
        )
        body = _json.dumps({"X": [[0.5] * width] * 8})
        headers = {
            "Content-Type": "application/json",
            "X-Gordo-Tenant": "gate-flood",
            "X-Gordo-Priority": "best_effort",
        }
        while not stop.is_set():
            try:
                resp = sess.post(
                    url, data=body, headers=headers,
                    timeout=ctx.http_timeout,
                )
                key = str(resp.status_code)
            except Exception:
                key = "599"
            flood_statuses[key] = flood_statuses.get(key, 0) + 1

    threads = [
        threading.Thread(target=flood, daemon=True) for _ in range(4)
    ]
    with _Probe(
        ctx.base_url, ctx.project, ctx.traffic,
        http_timeout=ctx.http_timeout,
    ) as probe:
        for t in threads:
            t.start()
        time.sleep(max(ctx.settle_s * 2, 1.5))
        stop.set()
        for t in threads:
            t.join(timeout=15)
    qos1 = ctx.get_json("qos")

    def _sum(doc, section, want_class=None):
        cells = (doc.get("admission") or {}).get(section) or {}
        return sum(
            n for key, n in cells.items()
            if want_class is None or key.split("|")[1:2] == [want_class]
        )

    admitted_be = _sum(qos1, "admitted", "best_effort") - _sum(
        qos0, "admitted", "best_effort"
    )
    shed_all = _sum(qos1, "shed") - _sum(qos0, "shed")
    shed_be = _sum(qos1, "shed", "best_effort") - _sum(
        qos0, "shed", "best_effort"
    )
    precision = round(shed_be / shed_all, 4) if shed_all > 0 else None
    verdict.update(
        {
            "flood_target": target,
            "flood_statuses": flood_statuses,
            "non_200": probe.non_200 + probe.traffic_errors,
            "probe_requests": probe.requests,
            "probe_statuses": probe.statuses,
            "probe_p95_ms": probe.p95_ms(),
            "best_effort_admitted_delta": admitted_be,
            "shed_delta": shed_all,
            "shed_on_best_effort_delta": shed_be,
            "shed_precision": precision,
        }
    )
    if admitted_be + shed_be <= 0:
        fails.append(
            "the best_effort flood never classified (admitted + shed "
            "deltas are zero): the QoS request path is broken"
        )
    if precision is not None and precision < 0.9:
        fails.append(
            f"shed precision {precision} < 0.9: admission shed "
            "traffic outside the flooding class"
        )
    if verdict["non_200"]:
        fails.append(
            f"{verdict['non_200']} interactive non-200(s) during the "
            f"flood window (budget 0; statuses: {probe.statuses})"
        )
    return verdict, fails


_GATE_DRILLS = {
    "replica_crash_restart": _gate_reload_under_load,
    "gray_failure_slow_replica": _gate_latency_burn_probe,
    "tenant_noisy_neighbor": _gate_qos_fairness,
}


def run_promotion_gate(
    base_url: str,
    project: str,
    scenarios: Optional[List[str]] = None,
    traffic: Optional[Callable[[str], Any]] = None,
    http_timeout: float = 30.0,
    settle_s: float = 0.8,
) -> Dict[str, Any]:
    """Run the gate-mode drills for ``scenarios`` (default
    :data:`~gameday.scenarios.GATE_DEFAULT`) against one live replica
    and return the judged gate document. Unknown or non-gate-capable
    scenario names raise — a compiled spec naming them should have
    failed validation, and a silent skip would turn a declared gate
    into no gate."""
    names = list(scenarios if scenarios is not None else GATE_DEFAULT)
    for name in names:
        if name not in SCENARIOS:
            raise ValueError(
                f"unknown gameday scenario {name!r} "
                f"(known: {sorted(SCENARIOS)})"
            )
        if name not in _GATE_DRILLS:
            raise ValueError(
                f"scenario {name!r} has no gate-mode drill "
                f"(gate-capable: {sorted(_GATE_DRILLS)})"
            )
    ctx = _GateContext(base_url, project, traffic, http_timeout, settle_s)
    doc: Dict[str, Any] = {
        "schema": GATE_SCHEMA,
        "base_url": ctx.base_url,
        "scenarios": {},
    }
    for name in names:
        t0 = time.monotonic()
        try:
            verdict, fails = _GATE_DRILLS[name](ctx)
        except Exception as exc:
            logger.exception("gameday gate drill %s crashed", name)
            verdict, fails = (
                {"gate_mode": "crashed", "detected": False},
                [f"gate drill crashed: {type(exc).__name__}: {exc}"],
            )
        verdict["scenario"] = name
        verdict["wall_seconds"] = round(time.monotonic() - t0, 3)
        doc["scenarios"][name] = finalize_verdict(verdict, fails)
    doc["passed"] = all(
        v["passed"] for v in doc["scenarios"].values()
    ) and bool(doc["scenarios"])
    return doc
