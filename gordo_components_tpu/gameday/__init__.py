"""Mesh-scale game days: break the multi-host mesh ON PURPOSE, judge
every failure with the SLO/incident stack.

The reference system's value was surviving production at fleet scale;
this package is the drill hall. A :class:`~gameday.harness.GamedayMesh`
boots the REAL multi-process mesh (N server subprocesses + a live
watchman, the same shape ``tools/mesh_demo.py`` measures), puts it
under scoring load, injects a mesh-class failure from the scenario
catalog (``scenarios.py``), and judges the whole loop end to end with
the observability stack that production would use:

- **detect** — watchman's routing plane, SLO rollup and ``/incidents``
  correlation must see the failure (detection latency, burn peak,
  causal event ordering);
- **contain** — routing/hedging/quarantine must bound the blast radius
  (non-200s vs a DECLARED budget, no traffic to dead or gray replicas);
- **recover** — burn returns to zero, the routing version converges,
  subscribers re-attach.

Verdicts share the replay harness's envelope
(``replay/verdict.py``: ``failures``/``passed``), land in
``BENCH_DETAIL.json`` via bench's ``gameday`` leg, and the worst
scenarios gate fleet promotion (``gameday/gate.py`` + the ``gameday``
step kind in ``workflow/compiler.py``).

Fault injection over subprocess boundaries rides the ``GORDO_FAULTS``
env (``resilience/faults.py`` — including the transport-level
blackhole/refuse/reset kinds this PR adds); in-process injection uses
the same registry directly.
"""

from gordo_components_tpu.gameday.scenarios import (
    SCENARIOS,
    GamedayScenario,
    known_scenarios,
)

__all__ = ["SCENARIOS", "GamedayScenario", "known_scenarios"]
