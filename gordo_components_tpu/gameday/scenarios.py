"""The game-day scenario catalog and its judge.

Each :class:`GamedayScenario` is declarative: a name, the mesh shape it
needs, and the BOUNDS its verdict must satisfy. The drill itself (what
gets killed, partitioned or slowed, and what evidence is collected)
lives in ``gameday/harness.py``; the judge here turns evidence into a
verdict the same way the replay harness's ``Scenario.judge`` does —
every declared bound is popped and checked, leftovers fail loudly, and
the shared envelope (``replay/verdict.py``) stamps
``failures``/``passed``.

Bounds vocabulary (all optional):

- ``max_detection_latency_s`` — the observability stack must have seen
  the injected failure (``detected``) within this many seconds of
  injection;
- ``max_non200`` — containment: data-plane non-200s vs the scenario's
  declared budget (default 0);
- ``max_recovery_s`` — ``recovered`` must be True within this many
  seconds of the heal action;
- ``require_event_order`` — these event types must ALL appear in the
  fleet timeline, first occurrences in this causal order;
- ``min_routing_version_steps`` — the routing table must have stepped
  at least this many versions (clients poll off dead owners);
- ``min_hedge_wins`` — the hedging client must have raced the sick
  replica and won at least this often;
- ``min_reroutes`` — stale-table detection must actually have fired;
- ``max_routing_refreshes`` — the refresh-stampede guard: total
  routing-table installs stays bounded during the storm;
- ``min_drift_replicas`` — correlated drift must flag on at least this
  many replicas;
- ``max_drift_recovery_s`` — alias of ``max_recovery_s`` semantics for
  readability in the drift scenario (same check);
- ``min_distinct_reconnect_delays`` — the reconnect herd must have
  spread over at least this many DISTINCT jittered delays;
- ``require_all_subscribers_recovered`` — every push subscriber polled
  successfully again after the blip;
- ``min_burn_peak`` — the SLO burn must actually have peaked at or
  above this (the failure was visible, not theoretical);
- ``min_shed_precision`` — of everything admission shed during the
  drill, at least this fraction must have landed on the flooding class
  (vacuously 1.0 when nothing was shed — no sheds means nobody was
  mis-shed);
- ``min_class_burn_peak`` — the flooding class's own
  ``gordo_slo_burn_rate{class}`` must have peaked at or above this on
  the fleet rollup (the shed was goodput-driven, not just queue luck);
- ``max_interactive_p99_ratio`` — interactive p99 under flood over
  unloaded interactive p99 stays at or under this (the fairness
  headline number).

Load-level bounds that only hold with real parallelism go in
``multicore_bounds`` — the judge merges them only when the host has >=2
CPUs (the PR 13/14 single-core honesty rule); structural bounds stay in
``bounds`` and are asserted everywhere.
"""

from dataclasses import dataclass, field
from typing import Any, Dict, List

from gordo_components_tpu.replay.verdict import (
    check_detection,
    check_non200,
    finalize_verdict,
)

__all__ = [
    "SCENARIOS",
    "GATE_DEFAULT",
    "GamedayScenario",
    "known_scenarios",
]


@dataclass(frozen=True)
class GamedayScenario:
    name: str
    description: str
    mesh: str  # shape the drill needs: partitioned|replicated|push|streaming|qos
    bounds: Dict[str, Any] = field(default_factory=dict)
    multicore_bounds: Dict[str, Any] = field(default_factory=dict)
    # gate-capable scenarios have a bounded single-replica drill
    # (gameday/gate.py) the fleet compiler can run pre-promotion
    gate_capable: bool = False

    def judge(
        self, verdict: Dict[str, Any], single_core: bool = False
    ) -> List[str]:
        """Bounds -> failure strings (empty = drill passed)."""
        b = dict(self.bounds)
        if not single_core:
            b.update(self.multicore_bounds)
        fails: List[str] = []
        max_lat = b.pop("max_detection_latency_s", None)
        if max_lat is not None:
            check_detection(
                bool(verdict.get("detected")),
                verdict.get("detection_latency_s"),
                max_lat,
                f"scenario {self.name}: injected failure",
                fails,
            )
        check_non200(verdict, int(b.pop("max_non200", 0)), fails)
        max_rec = b.pop("max_recovery_s", b.pop("max_drift_recovery_s", None))
        if max_rec is not None:
            rec_s = verdict.get("recovery_s")
            if not verdict.get("recovered"):
                fails.append("recovery was never observed")
            elif rec_s is not None and rec_s > max_rec:
                fails.append(
                    f"recovery took {rec_s:.1f}s > {max_rec:.1f}s"
                )
        order = b.pop("require_event_order", None)
        if order:
            seq = [
                str(e.get("type"))
                for e in verdict.get("events", [])
                if isinstance(e, dict)
            ]
            last = -1
            for etype in order:
                if etype not in seq:
                    fails.append(
                        f"event {etype!r} missing from the fleet timeline"
                    )
                    continue
                i = seq.index(etype)
                if i < last:
                    fails.append(
                        f"event {etype!r} out of causal order "
                        f"(timeline: {seq})"
                    )
                last = max(last, i)
        min_vs = b.pop("min_routing_version_steps", None)
        if min_vs is not None and verdict.get(
            "routing_version_steps", 0
        ) < min_vs:
            fails.append(
                f"routing version stepped "
                f"{verdict.get('routing_version_steps', 0)} time(s) "
                f"< {min_vs}"
            )
        min_hw = b.pop("min_hedge_wins", None)
        if min_hw is not None and verdict.get("hedge_wins", 0) < min_hw:
            fails.append(
                f"hedge wins {verdict.get('hedge_wins', 0)} < {min_hw} "
                "(hedging never routed around the sick replica)"
            )
        min_rr = b.pop("min_reroutes", None)
        if min_rr is not None and verdict.get("reroutes", 0) < min_rr:
            fails.append(
                f"reroutes {verdict.get('reroutes', 0)} < {min_rr} "
                "(stale-table detection never fired)"
            )
        max_rf = b.pop("max_routing_refreshes", None)
        if max_rf is not None and verdict.get(
            "routing_refreshes", 0
        ) > max_rf:
            fails.append(
                f"{verdict.get('routing_refreshes')} routing refreshes "
                f"> budget {max_rf} (refresh stampede)"
            )
        min_dr = b.pop("min_drift_replicas", None)
        if min_dr is not None and len(
            verdict.get("drifted_replicas", [])
        ) < min_dr:
            fails.append(
                f"drift flagged on {verdict.get('drifted_replicas')} "
                f"(< {min_dr} replicas) — correlation missed"
            )
        min_dd = b.pop("min_distinct_reconnect_delays", None)
        if min_dd is not None and verdict.get(
            "distinct_reconnect_delays", 0
        ) < min_dd:
            fails.append(
                f"{verdict.get('distinct_reconnect_delays', 0)} distinct "
                f"reconnect delays < {min_dd} (the herd did not spread)"
            )
        if b.pop("require_all_subscribers_recovered", False):
            lost = verdict.get("subscribers_lost", [])
            if lost:
                fails.append(f"subscribers never recovered: {lost}")
        min_bp = b.pop("min_burn_peak", None)
        if min_bp is not None and (
            verdict.get("burn_peak") is None
            or verdict["burn_peak"] < min_bp
        ):
            fails.append(
                f"burn peak {verdict.get('burn_peak')} < {min_bp} "
                "(the failure never showed on the SLO surface)"
            )
        min_sp = b.pop("min_shed_precision", None)
        if min_sp is not None:
            # vacuous pass at 1.0: zero sheds means zero MIS-sheds —
            # the bound is about who got hit, not whether anyone did
            prec = verdict.get("shed_precision")
            if prec is None:
                prec = 1.0
            if prec < min_sp:
                fails.append(
                    f"shed precision {prec:.3f} < {min_sp} (sheds "
                    "landed on the wrong class — fairness failed)"
                )
        min_cbp = b.pop("min_class_burn_peak", None)
        if min_cbp is not None and (
            verdict.get("class_burn_peak") is None
            or verdict["class_burn_peak"] < min_cbp
        ):
            fails.append(
                f"flooding-class burn peak "
                f"{verdict.get('class_burn_peak')} < {min_cbp} "
                "(the flood never burned its own class budget — "
                "shed was not goodput-attributed)"
            )
        max_ipr = b.pop("max_interactive_p99_ratio", None)
        if max_ipr is not None:
            ratio = verdict.get("interactive_p99_ratio")
            if ratio is None:
                fails.append(
                    "interactive p99 ratio was never measured "
                    "(baseline or flood phase produced no latencies)"
                )
            elif ratio > max_ipr:
                fails.append(
                    f"interactive p99 under flood = {ratio:.2f}x "
                    f"unloaded > {max_ipr}x (the flood starved "
                    "interactive latency)"
                )
        if b:
            fails.append(f"unknown bounds: {sorted(b)}")
        return fails

    def finalize(
        self, verdict: Dict[str, Any], single_core: bool = False
    ) -> Dict[str, Any]:
        verdict.setdefault("scenario", self.name)
        verdict.setdefault("description", self.description)
        verdict["single_core"] = bool(single_core)
        return finalize_verdict(verdict, self.judge(verdict, single_core))


# --------------------------------------------------------------------- #
# the catalog (docs/operations.md "Game days" is the operator's view)
# --------------------------------------------------------------------- #

SCENARIOS: Dict[str, GamedayScenario] = {
    s.name: s
    for s in [
        GamedayScenario(
            name="replica_crash_restart",
            description=(
                "SIGKILL one partitioned replica under scoring load; "
                "watchman must mark it unreachable (version step + "
                "mesh.replica_unreachable), surviving members must keep "
                "answering 200, and the respawned replica must rejoin "
                "the table."
            ),
            mesh="partitioned",
            bounds={
                "max_detection_latency_s": 20.0,
                # healthy members' responses — the blast radius must
                # stop at the dead replica's partition
                "max_non200": 0,
                "max_recovery_s": 150.0,
                "require_event_order": [
                    "mesh.replica_unreachable",
                    "mesh.replica_recovered",
                ],
                "min_routing_version_steps": 2,
            },
            gate_capable=True,
        ),
        GamedayScenario(
            name="watchman_partition",
            description=(
                "Transport-partition watchman from every replica "
                "(watchman.probe=refuse): the table must mark the fleet "
                "unreachable and step its version, while the DATA plane "
                "keeps serving 200s from the last-good table; healing "
                "the partition must converge the table back."
            ),
            mesh="partitioned",
            bounds={
                "max_detection_latency_s": 15.0,
                "max_non200": 0,
                "max_recovery_s": 30.0,
                "require_event_order": [
                    "mesh.replica_unreachable",
                    "mesh.replica_recovered",
                ],
                "min_routing_version_steps": 2,
            },
        ),
        GamedayScenario(
            name="migration_storm",
            description=(
                "Back-to-back migrations of one member while a routed "
                "client scores the fleet: stale-table 404s must resolve "
                "via ONE bounded refetch+re-post each (reroutes), "
                "refreshes must stay bounded (no stampede against "
                "watchman), and every prediction must end 200."
            ),
            mesh="partitioned",
            bounds={
                "max_non200": 0,
                "min_reroutes": 1,
                "max_routing_refreshes": 12,
                "min_routing_version_steps": 2,
            },
        ),
        GamedayScenario(
            name="gray_failure_slow_replica",
            description=(
                "One replicated replica is alive but slow (injected "
                "engine latency via GORDO_FAULTS): health gating says "
                "ok, so HEDGING is the containment — the client must "
                "race the sick replica's p95 and win on the healthy "
                "one; the sick replica's latency SLO must burn on the "
                "watchman rollup; burn decays once the fault budget is "
                "exhausted."
            ),
            mesh="replicated",
            bounds={
                "max_detection_latency_s": 30.0,
                "max_non200": 0,
                "min_hedge_wins": 1,
                "min_burn_peak": 1.0,
                "max_recovery_s": 90.0,
            },
            multicore_bounds={
                "min_hedge_wins": 3,
            },
            gate_capable=True,
        ),
        GamedayScenario(
            name="thundering_herd",
            description=(
                "A push replica with flaky transport (server."
                "connection=reset over GORDO_FAULTS) is killed and "
                "respawned under N long-poll subscribers: every "
                "subscriber must reconnect and poll again, with "
                "decorrelated-jitter delays spreading the herd; "
                "watchman must see the blip (version step + "
                "replica_unreachable/recovered)."
            ),
            mesh="push",
            bounds={
                "max_detection_latency_s": 20.0,
                "max_non200": 0,
                "require_all_subscribers_recovered": True,
                "min_distinct_reconnect_delays": 4,
                "require_event_order": [
                    "mesh.replica_unreachable",
                    "mesh.replica_recovered",
                ],
                "min_routing_version_steps": 2,
            },
        ),
        GamedayScenario(
            name="tenant_noisy_neighbor",
            description=(
                "A best_effort tenant floods the replicated fleet while "
                "a steady interactive client keeps scoring: weighted-"
                "fair batching + per-class admission must keep every "
                "interactive prediction 200 with bounded p99, land "
                ">=90% of the sheds on the flooding class, and show the "
                "flood burning its OWN class budget on the watchman "
                "per-class rollup — the noisy neighbor pays, the quiet "
                "one does not."
            ),
            # own mesh shape: the replicated shape arms a latency fault
            # for the gray-failure drill, which would pollute this
            # scenario's p99 baseline; qos boots clean with a tight
            # engine queue + per-class SLO windows instead
            mesh="qos",
            bounds={
                # interactive traffic only — the flood is EXPECTED to
                # eat 429s, so its non-200s are excluded by the runner
                "max_non200": 0,
                "min_shed_precision": 0.9,
            },
            multicore_bounds={
                # latency-fairness and burn-visibility bounds only hold
                # when the flood and the probe truly run concurrently
                "max_interactive_p99_ratio": 1.5,
                "min_class_burn_peak": 1.0,
            },
            gate_capable=True,
        ),
        GamedayScenario(
            name="correlated_drift",
            description=(
                "The same upstream shift hits members on EVERY replica "
                "at once (correlated drift): each replica's detector "
                "must flag (drift.flagged on >=2 replicas), the "
                "watchman drift rollup must union the attribution, "
                "scoring must stay 200 throughout, and recalibration "
                "must clear the flags fleet-wide."
            ),
            mesh="streaming",
            bounds={
                "max_detection_latency_s": 60.0,
                "max_non200": 0,
                "min_drift_replicas": 2,
                "max_recovery_s": 120.0,
                # flag first, then the fix lands (adapt resets the flag
                # itself, so the causal pair is flagged -> adapted)
                "require_event_order": ["drift.flagged", "adapt.recalibrate"],
            },
        ),
    ]
}

# the default pre-promotion gate set: the scenarios whose single-replica
# drills catch the failure modes a rollout can actually ship (a canary
# that 5xxs under swap, a canary that answers but is slow)
GATE_DEFAULT = ["replica_crash_restart", "gray_failure_slow_replica"]


def known_scenarios() -> List[str]:
    return sorted(SCENARIOS)
