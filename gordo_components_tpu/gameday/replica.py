"""Child-process entry for game-day replicas.

``python -m gordo_components_tpu.gameday.replica --root DIR --port N``
boots ONE real serving replica over the shared artifact dir — the same
process shape production runs and ``tools/mesh_demo.py`` measures. The
interesting configuration all rides the environment the harness sets
before spawning: mesh identity (``GORDO_MESH_REPLICA_ID`` /
``GORDO_MESH_REPLICAS``), the streaming/push planes (``GORDO_STREAM``,
``GORDO_PUSH``), observability cadence, and — the point of this package
— ``GORDO_FAULTS``, which ``server.build_app`` arms at boot, so a fault
injected by the parent is live inside a process boundary away.
"""

import argparse
import os


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", required=True, help="shared artifact dir")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args()

    from gordo_components_tpu.server import run_server

    run_server(args.root, host=args.host, port=args.port)


if __name__ == "__main__":
    main()
