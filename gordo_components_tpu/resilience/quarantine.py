"""Serving-side model quarantine: the per-model circuit breaker.

A model whose scoring keeps failing — a poisoned artifact, a bucket
program that emits NaN for its slot, a divergence that trained thresholds
can't mask — must not keep absorbing requests through the crash-retry
path forever, and must *definitely* not take the rest of the collection
down with it. :class:`QuarantineSet` counts consecutive scoring failures
(exceptions and non-finite outputs both) per model; at ``threshold`` the
model is evicted from routing: ``/prediction`` answers 410 with the
recorded reason, the name is listed in ``/stats`` and the
``gordo_quarantined_models`` gauge, and the server's tri-state
``/healthz`` reports ``degraded`` (not ``unhealthy`` — the healthy subset
is still serving, and a flapping liveness probe would turn one bad model
into a fleet-wide restart storm).

Clearing is an operator action (``POST .../quarantine/clear``) or a
``/reload`` that actually replaces the model — matching the runbook in
``docs/operations.md``.

Single-writer contract: all mutation happens on the aiohttp event-loop
thread (the same contract ``app["stats"]`` relies on); plain dict/int
state needs no locks.
"""

import logging
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

DEFAULT_THRESHOLD = 3


class QuarantineSet:
    """Consecutive-failure breaker over model names.

    ``threshold <= 0`` disables quarantining entirely (records nothing,
    contains nothing) — the operator's escape hatch.
    """

    def __init__(self, threshold: int = DEFAULT_THRESHOLD):
        self.threshold = int(threshold)
        self._failures: Dict[str, int] = {}  # pre-quarantine streaks
        self._last_reason: Dict[str, str] = {}
        self._quarantined: Dict[str, Dict[str, Any]] = {}

    # --------------------------- recording ---------------------------- #

    def record_failure(self, name: str, reason: str) -> bool:
        """Count one scoring failure; returns True when this failure
        newly quarantines the model."""
        if self.threshold <= 0 or name in self._quarantined:
            return False
        streak = self._failures.get(name, 0) + 1
        self._failures[name] = streak
        self._last_reason[name] = reason
        if streak < self.threshold:
            return False
        self._quarantined[name] = {
            "reason": reason,
            "failures": streak,
            "since": time.time(),
        }
        self._failures.pop(name, None)
        self._last_reason.pop(name, None)
        logger.error(
            "Model %r QUARANTINED after %d consecutive scoring failures "
            "(last: %s); /prediction now answers 410 until cleared",
            name, streak, reason,
        )
        return True

    def record_success(self, name: str) -> None:
        """A good score resets the pre-quarantine streak (quarantined
        models never reach scoring, so there is nothing to reset there)."""
        if self._failures:
            self._failures.pop(name, None)
            self._last_reason.pop(name, None)

    # ---------------------------- queries ----------------------------- #

    def __contains__(self, name: str) -> bool:
        return name in self._quarantined

    def __len__(self) -> int:
        return len(self._quarantined)

    def reason(self, name: str) -> Optional[Dict[str, Any]]:
        return self._quarantined.get(name)

    def names(self) -> List[str]:
        return sorted(self._quarantined)

    def snapshot(self) -> Dict[str, Any]:
        """Operator view for ``/stats`` and ``GET .../quarantine``."""
        return {
            "threshold": self.threshold,
            "quarantined": {
                name: dict(info) for name, info in sorted(self._quarantined.items())
            },
            "failing": {
                name: {"failures": n, "last_reason": self._last_reason.get(name, "")}
                for name, n in sorted(self._failures.items())
            },
        }

    # --------------------------- clearing ----------------------------- #

    def clear(self, names: Optional[List[str]] = None) -> List[str]:
        """Clear specific models (or everything when ``names`` is None);
        returns the names actually cleared. Their failure streaks restart
        from zero — a cleared model gets a full fresh allowance."""
        targets = sorted(self._quarantined) if names is None else names
        cleared = []
        for name in targets:
            if self._quarantined.pop(name, None) is not None:
                cleared.append(name)
            self._failures.pop(name, None)
            self._last_reason.pop(name, None)
        if cleared:
            logger.warning("Quarantine cleared for: %s", ", ".join(cleared))
        return cleared

    def drop(self, name: str) -> None:
        """Forget all state for a removed/replaced model (reload path)."""
        self._quarantined.pop(name, None)
        self._failures.pop(name, None)
        self._last_reason.pop(name, None)
