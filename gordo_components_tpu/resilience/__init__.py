"""Fleet resilience: deterministic fault injection + graceful degradation.

The platform's whole value is keeping thousands of per-machine models
built and servable when individual artifacts, pods, or scrapes fail
(PAPER.md §0: one corrupt artifact must not take down a fleet), and at
TPU-fleet scale the dominant efficiency loss is unhandled failures, not
raw step time ("ML Productivity Goodput", PAPERS.md). The defenses are
only real if they can be *exercised*: this package provides

- :mod:`faults` — a registry of named **faultpoints** threaded through
  the real failure sites (artifact load, bucket compile, scoring,
  engine queue, watchman scrapes, fleet-group training, checkpoint IO).
  Disabled by default with near-zero hot-path cost; armed per-site from
  code or the ``GORDO_FAULTS`` env var with deterministic raise-N-times,
  seeded probabilistic raise, and injected-latency modes. The chaos
  suite (``tests/test_chaos.py``, ``make chaos``) drives every
  registered site one at a time through the public HTTP/build APIs and
  asserts the process survives in its documented degraded state.
- :mod:`quarantine` — :class:`QuarantineSet`, the serving-side breaker:
  a model that repeatedly fails scoring or emits non-finite scores is
  evicted from routing (410 with a reason instead of a crash-retry
  loop) while the rest of the collection keeps serving; the server's
  tri-state ``/healthz`` reports ``degraded`` instead of flapping.
- :mod:`deadline` — per-request time budgets (``X-Gordo-Deadline-Ms``
  header -> :class:`Deadline` -> engine/bank drop-before-dispatch ->
  HTTP 504 :class:`DeadlineExceeded`), plus the shared
  ``Deadline.wait_for`` bound watchman's scrape/refresh paths reuse.
- :mod:`retry_budget` — :class:`RetryBudget` (token-bucket cap on
  client re-offered load) and decorrelated-jitter backoff, the client
  half of the overload defense.
"""

from gordo_components_tpu.resilience.deadline import (
    DEADLINE_HEADER,
    Deadline,
    DeadlineExceeded,
    default_deadline_ms,
    parse_deadline_ms,
)
from gordo_components_tpu.resilience.faults import (
    FaultInjected,
    FaultSpec,
    arm,
    configure_from_env,
    disarm,
    fault_stats,
    faultpoint,
    registered_sites,
    reset,
)
from gordo_components_tpu.resilience.quarantine import QuarantineSet
from gordo_components_tpu.resilience.retry_budget import (
    RetryBudget,
    decorrelated_jitter,
)

__all__ = [
    "DEADLINE_HEADER",
    "Deadline",
    "DeadlineExceeded",
    "FaultInjected",
    "FaultSpec",
    "QuarantineSet",
    "RetryBudget",
    "arm",
    "configure_from_env",
    "decorrelated_jitter",
    "default_deadline_ms",
    "disarm",
    "fault_stats",
    "faultpoint",
    "parse_deadline_ms",
    "registered_sites",
    "reset",
]
