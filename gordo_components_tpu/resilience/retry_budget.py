"""Client-side retry budget + decorrelated-jitter backoff.

Two halves of the same overload defense (the client-side complement to
the server's enqueue-time shedding and deadline drops):

- :class:`RetryBudget` — a token bucket bounding how much EXTRA load a
  retrying client may add (the gRPC/Finagle "retry budget" design).
  Every first attempt deposits ``ratio`` tokens; every retry withdraws
  one whole token. With ``ratio=0.1`` a client can re-offer at most
  ~10% of its offered load no matter how the fleet is failing —
  arithmetic, not configuration discipline, caps the retry storm below
  1.1x. The budget is SHARED across a client's concurrent chunks: the
  whole backfill run gets one bucket, so a thousand chunks failing
  together cannot each claim their private 3 retries.

- :func:`decorrelated_jitter` — the backoff schedule that replaces the
  deterministic ``backoff * 2**attempt``. Deterministic exponential
  backoff SYNCHRONIZES: chunks that failed together (one shed burst,
  one replica restart) sleep the same time and re-arrive together,
  re-creating the overload they backed off from, forever. Decorrelated
  jitter (`sleep = uniform(base, prev * 3)`, capped) spreads each
  retry wave thinner than the last (the AWS architecture-blog result).
"""

import random
import threading
from typing import Dict, Optional

__all__ = ["RetryBudget", "decorrelated_jitter"]


def decorrelated_jitter(
    base: float,
    prev: float,
    cap: float = 60.0,
    rng: Optional[random.Random] = None,
) -> float:
    """Next sleep in a decorrelated-jitter schedule.

    ``base`` is the configured backoff floor, ``prev`` the previous
    sleep (pass ``base`` on the first retry). Grows in EXPECTATION like
    exponential backoff but two clients never share a schedule.
    """
    r = rng.uniform if rng is not None else random.uniform
    return min(max(0.0, cap), r(base, max(base, prev * 3.0)))


class RetryBudget:
    """Token-bucket retry admission shared across concurrent requests.

    ``note_request()`` (called once per logical request) deposits
    ``ratio`` tokens; ``try_spend()`` withdraws one token per retry and
    answers whether the retry is allowed. ``initial`` pre-fills the
    bucket so a small burst of early failures can still retry before
    any deposits accumulate; ``max_tokens`` bounds how much unused
    budget can bank up (a quiet hour must not fund a retry storm
    later). Thread-safe: the bulk client records from the event loop
    but the lock keeps the type safe for executor use too.
    """

    def __init__(
        self,
        ratio: float = 0.1,
        initial: float = 10.0,
        max_tokens: float = 100.0,
    ):
        if ratio < 0:
            raise ValueError(f"ratio must be >= 0, got {ratio!r}")
        if max_tokens <= 0:
            raise ValueError(f"max_tokens must be positive, got {max_tokens!r}")
        self.ratio = float(ratio)
        self.max_tokens = float(max_tokens)
        self.tokens = min(float(initial), self.max_tokens)
        self.requests = 0
        self.allowed = 0  # retries the budget admitted
        self.denied = 0  # retries the budget refused
        self._lock = threading.Lock()

    def note_request(self) -> None:
        """One logical request offered: deposit the earned retry
        fraction."""
        with self._lock:
            self.requests += 1
            self.tokens = min(self.max_tokens, self.tokens + self.ratio)

    def try_spend(self) -> bool:
        """Withdraw one retry token; False = budget exhausted, do NOT
        retry (fail fast — the fleet is already saturated with the
        first-offer load)."""
        with self._lock:
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                self.allowed += 1
                return True
            self.denied += 1
            return False

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return {
                "tokens": round(self.tokens, 3),
                "ratio": self.ratio,
                "requests": self.requests,
                "retries_allowed": self.allowed,
                "retries_denied": self.denied,
            }

    def __repr__(self) -> str:
        s = self.snapshot()
        return (
            f"<RetryBudget tokens={s['tokens']} ratio={s['ratio']} "
            f"allowed={s['retries_allowed']} denied={s['retries_denied']}>"
        )
