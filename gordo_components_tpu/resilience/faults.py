"""Deterministic fault-injection registry.

Every real failure site in the stack declares a module-level
:func:`faultpoint` and trips it on its hot path::

    _FP_LOAD = faultpoint("model_io.load")   # import time: registers the site

    def _load_one(...):
        _FP_LOAD.fire()                      # no-op unless armed
        ...

Disabled cost is one method call reading one slot attribute against
``None`` — no env reads, no locks, no allocation — guarded by the 5%
hot-loop overhead test (``tests/test_chaos.py``, the PR-1 pattern). A
point also works as a context manager (fires on ``__enter__``) and as a
decorator (fires before the wrapped call) for sites where that reads
better.

Arming is explicit (:func:`arm`) or env-driven (:func:`configure_from_env`
reading ``GORDO_FAULTS``), with three modes composable per spec:

- **raise-N-times**: ``times=N`` — the first N ``fire()`` calls raise,
  later ones pass (deterministic "transient failure");
- **probabilistic**: ``p=0.25,seed=7`` — a *seeded* private RNG decides
  each fire, so a chaos run replays identically;
- **latency**: ``latency:0.05`` — sleep before (optionally) raising.

``GORDO_FAULTS`` grammar (';'-separated clauses)::

    site=kind[:arg][,key=value...]

    GORDO_FAULTS="model_io.load=error:OSError,times=2;bank.score=latency:0.05"
    GORDO_FAULTS="watchman.scrape=error,p=0.5,seed=42"

``kind`` is ``error`` (arg: exception class name, default
:class:`FaultInjected`) or ``latency`` (arg: seconds, raises nothing
unless ``error=Name`` is added), plus three transport-level kinds the
mesh game days drive over subprocess boundaries (a site placed on a
connection-handling path — e.g. ``server.connection`` — turns these
into real socket-level failures):

- ``refuse`` — raises :class:`ConnectionRefusedError` (the peer's port
  answers RST: process down, nothing listening);
- ``reset`` — raises :class:`ConnectionResetError` (the connection died
  mid-exchange: crash after accept, middlebox cut);
- ``blackhole[:seconds]`` — sleeps (default 5s: packets silently
  dropped, the caller hangs until its own deadline) then raises
  :class:`TimeoutError`.

Unknown sites are accepted — arming may precede the importing of the
module that registers the site.
"""

import logging
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "FaultInjected",
    "FaultSpec",
    "arm",
    "configure_from_env",
    "disarm",
    "fault_stats",
    "faultpoint",
    "registered_sites",
    "reset",
    "set_fire_listener",
]

# one process-wide observer invoked on every ACTUAL fire (after the
# p/times gates pass, before any delay/raise) — the event timeline's
# hook. A plain module global read once per armed fire: the disabled
# hot path (spec is None) never reaches it, preserving the 5% guard.
_FIRE_LISTENER: Optional[Callable[[str, "FaultSpec"], None]] = None


def set_fire_listener(
    fn: Optional[Callable[[str, "FaultSpec"], None]]
) -> Optional[Callable[[str, "FaultSpec"], None]]:
    """Install (or clear with None) the fire observer; returns the
    previous one. Listener exceptions are swallowed — observability must
    never change injected-fault semantics."""
    global _FIRE_LISTENER
    prev = _FIRE_LISTENER
    _FIRE_LISTENER = fn
    return prev


class FaultInjected(RuntimeError):
    """Default exception raised by an armed error faultpoint."""


# exception classes an env spec may name: builtins only (arbitrary import
# paths from an env var would be an injection surface, not a test knob)
import builtins as _builtins

_ALLOWED_EXCEPTIONS: Dict[str, type] = {
    name: exc
    for name, exc in vars(_builtins).items()
    if isinstance(exc, type) and issubclass(exc, Exception)
}
_ALLOWED_EXCEPTIONS["FaultInjected"] = FaultInjected


class FaultSpec:
    """One armed behavior: what happens when its site fires."""

    __slots__ = ("exc", "delay_s", "times", "p", "_rng", "remaining", "fired")

    def __init__(
        self,
        exc: Optional[type] = FaultInjected,
        delay_s: float = 0.0,
        times: Optional[int] = None,
        p: float = 1.0,
        seed: Optional[int] = None,
    ):
        if exc is not None and not (
            isinstance(exc, type) and issubclass(exc, BaseException)
        ):
            raise TypeError(f"exc must be an exception class, got {exc!r}")
        if not 0.0 <= float(p) <= 1.0:
            raise ValueError(f"p must be in [0, 1], got {p!r}")
        self.exc = exc
        self.delay_s = float(delay_s)
        self.times = None if times is None else int(times)
        self.p = float(p)
        # private seeded stream: a chaos run replays identically and never
        # perturbs global random state
        self._rng = random.Random(0 if seed is None else seed)
        self.remaining = self.times
        self.fired = 0

    def fire(self, site: str) -> None:
        if self.p < 1.0 and self._rng.random() >= self.p:
            return
        if self.remaining is not None:
            if self.remaining <= 0:
                return
            self.remaining -= 1
        self.fired += 1
        listener = _FIRE_LISTENER
        if listener is not None:
            try:
                listener(site, self)
            except Exception:
                pass
        if self.delay_s > 0:
            time.sleep(self.delay_s)
        if self.exc is not None:
            raise self.exc(f"fault injected at {site!r}")

    def describe(self) -> Dict[str, Any]:
        return {
            "exception": None if self.exc is None else self.exc.__name__,
            "delay_s": self.delay_s,
            "times": self.times,
            "remaining": self.remaining,
            "p": self.p,
            "fired": self.fired,
        }


class FaultPoint:
    """A named injection site. ``_spec`` is the only hot-path state:
    ``None`` (the overwhelmingly common case) means pass through."""

    __slots__ = ("site", "_spec")

    def __init__(self, site: str):
        self.site = site
        self._spec: Optional[FaultSpec] = None

    def fire(self) -> None:
        """Inline trigger — the hot-path form."""
        spec = self._spec
        if spec is not None:
            spec.fire(self.site)

    # context-manager form: fires on entry, guards the whole block
    def __enter__(self) -> "FaultPoint":
        self.fire()
        return self

    def __exit__(self, *exc_info) -> bool:
        return False

    # decorator form
    def __call__(self, fn: Callable) -> Callable:
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            self.fire()
            return fn(*args, **kwargs)

        return wrapper

    def __repr__(self) -> str:
        state = "disarmed" if self._spec is None else f"armed({self._spec.describe()})"
        return f"<faultpoint {self.site!r} {state}>"


# site name -> FaultPoint; insertion ordered, grown at import time by the
# modules that own the sites (so `registered_sites()` enumerates exactly
# the failure surfaces the chaos suite must drive)
_POINTS: Dict[str, FaultPoint] = {}
# specs armed before their site's module imported: applied on registration
_PENDING: Dict[str, FaultSpec] = {}


def faultpoint(site: str) -> FaultPoint:
    """Get-or-create the :class:`FaultPoint` for ``site`` (registering it)."""
    point = _POINTS.get(site)
    if point is None:
        point = _POINTS[site] = FaultPoint(site)
        pending = _PENDING.pop(site, None)
        if pending is not None:
            point._spec = pending
    return point


def registered_sites() -> List[str]:
    """Every site declared so far (import the subsystem first)."""
    return sorted(_POINTS)


def arm(site: str, spec: Optional[FaultSpec] = None, **kwargs: Any) -> FaultSpec:
    """Arm ``site`` with ``spec`` (or ``FaultSpec(**kwargs)``).

    Arming an unregistered site parks the spec until the owning module
    registers it — env configuration runs before subsystem imports.
    """
    if spec is None:
        spec = FaultSpec(**kwargs)
    point = _POINTS.get(site)
    if point is None:
        _PENDING[site] = spec
    else:
        point._spec = spec
    logger.warning("FAULT INJECTION armed at %r: %s", site, spec.describe())
    return spec


def disarm(site: str) -> None:
    point = _POINTS.get(site)
    if point is not None:
        point._spec = None
    _PENDING.pop(site, None)


def reset() -> None:
    """Disarm every site (test teardown)."""
    for point in _POINTS.values():
        point._spec = None
    _PENDING.clear()


def fault_stats() -> Dict[str, Dict[str, Any]]:
    """site -> spec description for every armed site (operator/debug view)."""
    out = {
        site: p._spec.describe() for site, p in _POINTS.items() if p._spec is not None
    }
    for site, spec in _PENDING.items():
        out[site] = spec.describe()
    return out


# ------------------------------------------------------------------ #
# env-driven configuration
# ------------------------------------------------------------------ #

ENV_VAR = "GORDO_FAULTS"


def _parse_clause(clause: str) -> tuple:
    site, _, spec_str = clause.partition("=")
    site, spec_str = site.strip(), spec_str.strip()
    if not site or not spec_str:
        raise ValueError(f"malformed fault clause {clause!r} (want site=spec)")
    head, *opts = spec_str.split(",")
    kind, _, arg = head.partition(":")
    kind = kind.strip().lower()
    kwargs: Dict[str, Any] = {}
    if kind == "error":
        if arg:
            exc = _ALLOWED_EXCEPTIONS.get(arg.strip())
            if exc is None:
                raise ValueError(
                    f"unknown exception {arg.strip()!r} in fault clause "
                    f"{clause!r} (builtin exceptions and FaultInjected only)"
                )
            kwargs["exc"] = exc
    elif kind == "latency":
        kwargs["delay_s"] = float(arg or 0.01)
        kwargs["exc"] = None
    elif kind == "refuse":
        if arg:
            raise ValueError(
                f"fault kind 'refuse' takes no argument (got {arg!r} in "
                f"{clause!r})"
            )
        kwargs["exc"] = ConnectionRefusedError
    elif kind == "reset":
        if arg:
            raise ValueError(
                f"fault kind 'reset' takes no argument (got {arg!r} in "
                f"{clause!r})"
            )
        kwargs["exc"] = ConnectionResetError
    elif kind == "blackhole":
        # a blackhole HANGS the caller (dropped packets, no RST) before
        # surfacing as a timeout — delay first, TimeoutError after
        kwargs["delay_s"] = float(arg or 5.0)
        kwargs["exc"] = TimeoutError
    else:
        raise ValueError(
            f"unknown fault kind {kind!r} in {clause!r} "
            "(error|latency|blackhole|refuse|reset)"
        )
    for opt in opts:
        k, _, v = opt.partition("=")
        k, v = k.strip(), v.strip()
        if k == "times":
            kwargs["times"] = int(v)
        elif k == "p":
            kwargs["p"] = float(v)
        elif k == "seed":
            kwargs["seed"] = int(v)
        elif k == "latency":
            kwargs["delay_s"] = float(v)
        elif k == "error":
            exc = _ALLOWED_EXCEPTIONS.get(v)
            if exc is None:
                raise ValueError(f"unknown exception {v!r} in {clause!r}")
            kwargs["exc"] = exc
        else:
            raise ValueError(f"unknown fault option {k!r} in {clause!r}")
    return site, FaultSpec(**kwargs)


def configure_from_env(value: Optional[str] = None) -> int:
    """Arm faultpoints from ``GORDO_FAULTS`` (or ``value``); returns the
    number of sites armed. A malformed spec raises — silently ignoring a
    typo'd chaos config would report a vacuous green run."""
    raw = os.environ.get(ENV_VAR, "") if value is None else value
    raw = raw.strip()
    if not raw:
        return 0
    n = 0
    for clause in raw.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        site, spec = _parse_clause(clause)
        arm(site, spec)
        n += 1
    return n
