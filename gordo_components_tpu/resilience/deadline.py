"""Per-request deadlines, propagated end to end.

Under fleet-backfill saturation the failure mode is not "the server is
slow" but "the server is busy answering clients that gave up seconds
ago": admitted work never expired, so every queued request eventually
burned a device dispatch whether or not anyone was still waiting — the
metastable-overload recipe ("ML Productivity Goodput", PAPERS.md). The
fix is a budget that travels WITH the request:

- the client stamps ``X-Gordo-Deadline-Ms`` (its remaining patience) on
  every scoring POST;
- the server middleware parses it (or applies the operator default
  ``GORDO_DEFAULT_DEADLINE_MS``) into a :class:`Deadline` carried on the
  request;
- the batching engine drops already-expired entries *before* device
  dispatch, resolving their futures with :class:`DeadlineExceeded`
  (HTTP 504), so TPU time is spent only on answers someone still wants;
- ``ModelBank.score_many`` checks the remaining budget between bucket
  group dispatches, so a multi-group batch stops mid-way instead of
  finishing work nobody will read.

:class:`DeadlineExceeded` subclasses :class:`asyncio.TimeoutError` so
existing best-effort call sites (watchman scrapes, the shared
``fetch_metadata_all`` helper) that already catch timeouts degrade the
same way for a blown deadline — one exception taxonomy for "out of
time" everywhere.

Deadlines are monotonic-clock absolute instants: immune to wall-clock
steps, comparable across the event loop and executor threads in one
process, and deliberately NOT serialized across hosts (the header
carries a relative budget in ms; each hop re-anchors it on its own
clock, the standard cross-host propagation trick).
"""

import asyncio
import math
import os
import time
from typing import Any, Awaitable, Optional

__all__ = [
    "DEADLINE_HEADER",
    "Deadline",
    "DeadlineExceeded",
    "MAX_DEADLINE_MS",
    "default_deadline_ms",
    "parse_deadline_ms",
]

DEADLINE_HEADER = "X-Gordo-Deadline-Ms"
ENV_DEFAULT = "GORDO_DEFAULT_DEADLINE_MS"

# clamp ceiling for client-supplied budgets: the header is attacker
# adjacent (any HTTP peer sets it) and a near-infinite float must not
# produce a deadline that never expires where the operator expected one
MAX_DEADLINE_MS = 24 * 3600 * 1e3


class DeadlineExceeded(asyncio.TimeoutError):
    """The request's time budget ran out before the work completed.

    Maps to HTTP 504 at the serving layer (with the request id, like the
    500/410 paths). Subclasses ``asyncio.TimeoutError`` so generic
    timeout handling (retry loops, best-effort scrapes) needs no new
    catch clause.
    """


class Deadline:
    """An absolute monotonic expiry instant with its original budget.

    Cheap by design: construction is one ``time.monotonic()`` read, and
    :meth:`expired` is one read + one compare — it sits on the engine's
    per-pending dispatch path (see the hotloop guard in
    ``tests/test_deadline.py``).
    """

    __slots__ = ("expires_at", "budget_s")

    def __init__(self, seconds: float):
        self.budget_s = max(0.0, float(seconds))
        self.expires_at = time.monotonic() + self.budget_s

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls(float(ms) / 1e3)

    def expired(self, now: Optional[float] = None) -> bool:
        """``now`` lets a batch loop reuse one clock read for N checks."""
        return (time.monotonic() if now is None else now) >= self.expires_at

    def remaining_s(self) -> float:
        """Seconds left; clamped at 0 (an expired deadline has no
        negative budget to hand downstream)."""
        return max(0.0, self.expires_at - time.monotonic())

    def remaining_ms(self) -> float:
        return self.remaining_s() * 1e3

    async def wait_for(self, awaitable: Awaitable[Any]) -> Any:
        """``asyncio.wait_for`` bounded by the REMAINING budget, raising
        :class:`DeadlineExceeded` — the shared helper behind watchman's
        scrape/refresh bounds and the client's per-attempt bound, so
        every "give up after" in the stack expires the same way."""
        try:
            return await asyncio.wait_for(awaitable, timeout=self.remaining_s())
        except asyncio.TimeoutError:
            raise DeadlineExceeded(
                f"deadline exceeded after {self.budget_s:.3f}s budget"
            ) from None

    def __repr__(self) -> str:
        return f"<Deadline budget={self.budget_s:.3f}s remaining={self.remaining_s():.3f}s>"


def parse_deadline_ms(raw: Optional[str]) -> Optional[float]:
    """Milliseconds from a ``X-Gordo-Deadline-Ms`` header value, or None.

    Malformed, non-finite, and non-positive values return None (the
    request proceeds under the server default) rather than 400: the
    header is best-effort metadata from heterogeneous clients/proxies,
    and rejecting the request over it would turn a telemetry hint into
    an outage. Values clamp to :data:`MAX_DEADLINE_MS`.
    """
    if not raw:
        return None
    try:
        ms = float(raw.strip())
    except (TypeError, ValueError):
        return None
    if not math.isfinite(ms) or ms <= 0:
        return None
    return min(ms, MAX_DEADLINE_MS)


def default_deadline_ms() -> Optional[float]:
    """Operator default budget from ``GORDO_DEFAULT_DEADLINE_MS``
    (milliseconds; unset/empty = no default). Malformed values RAISE —
    this deploys fleet-wide, and silently dropping a typo'd default
    would disable deadline protection with no signal (same contract as
    the server's other env knobs)."""
    raw = os.environ.get(ENV_DEFAULT, "").strip()
    if not raw:
        return None
    try:
        ms = float(raw)
    except ValueError:
        raise ValueError(
            f"{ENV_DEFAULT} must be a number of milliseconds, got {raw!r}"
        ) from None
    if not math.isfinite(ms) or ms <= 0:
        raise ValueError(
            f"{ENV_DEFAULT} must be a positive finite number of ms, got {raw!r}"
        )
    return min(ms, MAX_DEADLINE_MS)
