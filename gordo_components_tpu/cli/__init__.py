"""CLI (reference parity: gordo_components/cli/, unverified — SURVEY.md §2)."""

from gordo_components_tpu.cli.cli import gordo

__all__ = ["gordo"]
