"""``gordo-components-tpu`` command-line interface.

Reference parity: the ``gordo-components`` click group
(gordo_components/cli/cli.py, unverified; SURVEY.md §2 "cli"):
``build`` (env-var driven builder-pod entrypoint with distinct exit codes),
``run-server``, ``run-watchman``, ``client ...``, ``workflow generate`` —
plus the TPU-native ``build-fleet`` gang entrypoint.
"""

import json
import logging
import os
import sys

import click
import yaml

logger = logging.getLogger(__name__)

EXIT_OK = 0
EXIT_CONFIG_ERROR = 81
EXIT_DATA_ERROR = 82
EXIT_BUILD_ERROR = 83
# partial fleet build: SOME members shipped, the rest are recorded as
# failed in the manifest — distinct from EXIT_BUILD_ERROR so a retry
# controller can tell "rerun just the failures" from "rerun everything"
EXIT_PARTIAL_BUILD = 84


@click.group("gordo-components-tpu")
@click.option("--log-level", default="INFO", envvar="LOG_LEVEL")
@click.option("--platform", default=None, envvar="JAX_PLATFORMS",
              help="Pin the JAX backend (e.g. 'cpu', 'tpu'). Applied "
                   "in-process BEFORE any device use: an env var alone "
                   "cannot override a site-installed platform pin, and a "
                   "wedged accelerator plugin hangs rather than errors")
@click.option("--profile-dir", default=None, envvar="GORDO_PROFILE_DIR",
              help="Write jax.profiler traces of train/build hot sections "
                   "here (TensorBoard/Perfetto-viewable)")
@click.option("--compile-cache-dir", default=None,
              envvar="GORDO_COMPILE_CACHE_DIR",
              help="Persistent XLA compilation cache (a shared volume in "
                   "pods): restarted/preempted builders and rolling server "
                   "deploys reuse compiled programs instead of paying the "
                   "~tens-of-seconds-per-shape XLA compile again")
def gordo(log_level, platform, profile_dir, compile_cache_dir):
    """TPU-native gordo: build, serve, and orchestrate fleets of
    time-series anomaly-detection models."""
    logging.basicConfig(
        level=getattr(logging, log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    if compile_cache_dir:
        from gordo_components_tpu.utils import enable_compile_cache

        enable_compile_cache(compile_cache_dir)
    if profile_dir:
        os.environ["GORDO_PROFILE_DIR"] = profile_dir
    if os.environ.get("GORDO_FAULTS"):
        # chaos runs: arm the named faultpoints before any subsystem runs
        # (resilience/faults.py parks specs for sites not yet imported)
        from gordo_components_tpu.resilience import configure_from_env

        configure_from_env()


def _load_json_or_yaml(value: str):
    try:
        return json.loads(value)
    except json.JSONDecodeError:
        return yaml.safe_load(value)


@gordo.command("build")
@click.option("--name", envvar="MACHINE_NAME", required=True)
@click.option("--model-config", envvar="MODEL_CONFIG", required=True,
              help="JSON/YAML model definition (env MODEL_CONFIG)")
@click.option("--data-config", envvar="DATA_CONFIG", required=True,
              help="JSON/YAML dataset config (env DATA_CONFIG)")
@click.option("--metadata", envvar="METADATA", default="{}")
@click.option("--output-dir", envvar="OUTPUT_DIR", default="./model-output")
@click.option("--model-register-dir", envvar="MODEL_REGISTER_DIR", default=None)
@click.option("--evaluation-config", envvar="EVALUATION_CONFIG", default="{}",
              help="JSON/YAML evaluation block (env EVALUATION_CONFIG): "
                   '{"cv_mode": "full_build"|"cross_val_only", '
                   '"cross_validation": true, "n_splits": 3} — '
                   "TimeSeriesSplit CV scores land in artifact metadata")
@click.option("--print-cv-scores", is_flag=True)
def build(name, model_config, data_config, metadata, output_dir,
          model_register_dir, evaluation_config, print_cv_scores):
    """Build one model (builder-pod entrypoint; reference §3.1)."""
    from gordo_components_tpu import serializer
    from gordo_components_tpu.builder import provide_saved_model

    try:
        model_config = _load_json_or_yaml(model_config)
        data_config = _load_json_or_yaml(data_config)
        metadata = _load_json_or_yaml(metadata) or {}
        evaluation_config = _load_json_or_yaml(evaluation_config) or {}
    except yaml.YAMLError as exc:
        click.echo(f"Config parse error: {exc}", err=True)
        sys.exit(EXIT_CONFIG_ERROR)

    try:
        path = provide_saved_model(
            name, model_config, data_config, metadata,
            output_dir=output_dir, model_register_dir=model_register_dir,
            evaluation_config=evaluation_config,
        )
    except (ValueError, ImportError, FileNotFoundError) as exc:
        click.echo(f"Build failed (config/data): {exc}", err=True)
        sys.exit(EXIT_DATA_ERROR)
    except Exception as exc:
        click.echo(f"Build failed: {exc}", err=True)
        sys.exit(EXIT_BUILD_ERROR)

    built_metadata = serializer.load_metadata(path)
    if print_cv_scores:
        cv = built_metadata.get("model", {}).get("cross-validation", {})
        click.echo(json.dumps(cv.get("explained-variance", {})))
    click.echo(path)


@gordo.command("build-fleet")
@click.option("--machines-file", envvar="MACHINES_FILE", required=True,
              help="JSON/YAML file: gang payload or {machines: [...]}")
@click.option("--output-dir", envvar="OUTPUT_DIR", default="./model-output")
@click.option("--model-register-dir", envvar="MODEL_REGISTER_DIR", default=None)
@click.option("--checkpoint-dir", envvar="CHECKPOINT_DIR", default=None,
              help="Enable mid-training preemption recovery for fleet groups")
@click.option("--checkpoint-every", envvar="CHECKPOINT_EVERY", default=1, type=int,
              help="Epochs between fleet checkpoints (amortizes the "
                   "device-to-host state gather for large buckets)")
@click.option("--distributed", is_flag=True, envvar="GORDO_DISTRIBUTED",
              help="Multi-host gang: init jax.distributed and build only "
                   "this host's member slice")
@click.option("--state-dir", envvar="GANG_STATE_DIR", default=None,
              help="Publish gang heartbeats (phase/progress) here for "
                   "watchman to aggregate")
@click.option("--gang-id", envvar="GANG_ID", default=None,
              help="Heartbeat identity (default: hostname-pid)")
def build_fleet_cmd(machines_file, output_dir, model_register_dir, checkpoint_dir,
                    checkpoint_every, distributed, state_dir, gang_id):
    """Build a gang of machines in one process (TPU fleet engine)."""
    from gordo_components_tpu.builder.fleet_build import build_fleet
    from gordo_components_tpu.workflow.config import Machine

    with open(machines_file) as f:
        payload = yaml.safe_load(f)
    if isinstance(payload, dict):
        entries = payload.get("machines", [])
    elif isinstance(payload, list):
        entries = payload
    else:
        entries = []
    machines = []
    for e in entries:
        kwargs = dict(
            name=e["name"],
            dataset=e.get("dataset", {}),
            metadata=e.get("metadata", {}) or {},
            evaluation=e.get("evaluation", {}) or {},
        )
        if e.get("model"):  # absent -> Machine's default model config
            kwargs["model"] = e["model"]
        machines.append(Machine(**kwargs))
    if not machines:
        click.echo("No machines in payload", err=True)
        sys.exit(EXIT_CONFIG_ERROR)
    try:
        results = build_fleet(
            machines, output_dir, model_register_dir=model_register_dir,
            checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
            distributed=distributed, state_dir=state_dir, gang_id=gang_id,
        )
    except Exception as exc:
        click.echo(f"Fleet build failed: {exc}", err=True)
        sys.exit(EXIT_BUILD_ERROR)
    # partial-manifest contract (docs/operations.md runbook): the manifest
    # always lists built AND failed members, lands on disk next to the
    # artifacts for the retry controller, and the exit code distinguishes
    # "everything shipped" (0) / "partial — rerun the failed subset" (84)
    # / "nothing shipped" (83)
    manifest = results.manifest()
    try:
        os.makedirs(output_dir, exist_ok=True)
        with open(os.path.join(output_dir, "build_manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2)
    except OSError as exc:
        click.echo(f"warning: could not write build_manifest.json: {exc}", err=True)
    click.echo(json.dumps(manifest, indent=2))
    if results.failed and not results:
        sys.exit(EXIT_BUILD_ERROR)
    if results.failed:
        sys.exit(EXIT_PARTIAL_BUILD)


@gordo.command("checkpoint-prune")
@click.option("--checkpoint-dir", envvar="CHECKPOINT_DIR", required=True)
@click.option("--older-than-days", default=7.0, type=float,
              help="Delete bucket checkpoints untouched for this long")
def checkpoint_prune_cmd(checkpoint_dir, older_than_days):
    """Explicit janitor for stranded fleet checkpoints (checkpoints whose
    config/data key will never be computed again accumulate forever on a
    shared volume; pruning is deliberately NOT a side effect of builds)."""
    from gordo_components_tpu.parallel.checkpoint import prune_stale_checkpoints

    n = prune_stale_checkpoints(checkpoint_dir, older_than_days)
    click.echo(f"Pruned {n} stale checkpoint(s)")


@gordo.command("run-server")
@click.option("--model-dir", envvar="MODEL_COLLECTION_DIR", required=True)
@click.option("--host", default="0.0.0.0", envvar="SERVER_HOST")
@click.option("--port", default=5555, envvar="SERVER_PORT", type=int)
@click.option(
    "--devices", default=None, type=int, envvar="GORDO_SERVER_DEVICES",
    help="Shard the model bank over an N-device models-axis mesh "
    "(0/unset = all available devices when more than one is present).",
)
def run_server_cmd(model_dir, host, port, devices):
    """Serve the model collection under MODEL_COLLECTION_DIR."""
    from gordo_components_tpu.server import run_server

    run_server(model_dir, host=host, port=port, devices=devices)


@gordo.command("run-watchman")
@click.option("--project", envvar="PROJECT_NAME", required=True)
@click.option("--server-base-url", envvar="SERVER_BASE_URL", required=True)
@click.option("--targets", envvar="TARGET_NAMES", default=None,
              help="JSON list; discovered from the server when omitted")
@click.option("--gang-state-dir", envvar="GANG_STATE_DIR", default=None,
              help="Aggregate builder-gang heartbeats from this directory")
@click.option("--full-metadata", is_flag=True, envvar="WATCHMAN_FULL_METADATA",
              help="Aggregate FULL per-target metadata instead of the "
                   "bounded digest (digest keeps 10k-fleet snapshots under "
                   "~1 MB; full restores the reference-style aggregate)")
@click.option("--host", default="0.0.0.0")
@click.option("--port", default=5556, type=int)
def run_watchman_cmd(project, server_base_url, targets, gang_state_dir,
                     full_metadata, host, port):
    """Fleet health aggregation service."""
    from gordo_components_tpu.watchman import run_watchman

    target_list = json.loads(targets) if targets else None
    run_watchman(
        project, server_base_url, target_list, host=host, port=port,
        gang_state_dir=gang_state_dir, full_metadata=full_metadata,
    )


@gordo.group("client")
def client_group():
    """Bulk prediction client."""


@client_group.command("predict")
@click.argument("start")
@click.argument("end")
@click.option("--project", envvar="PROJECT_NAME", required=True)
@click.option("--base-url", default="http://localhost:5555")
@click.option("--target", multiple=True, help="Limit to specific machines")
@click.option("--parquet-dir", default=None, help="Forward results to parquet files")
@click.option("--batch-size", default=1000, type=int)
@click.option("--body-encoding", type=click.Choice(["auto", "json", "parquet"]),
              default="auto", envvar="GORDO_CLIENT_ENCODING",
              help="Scoring POST body encoding: auto negotiates parquet "
                   "when the server advertises it (2.3x JSON throughput "
                   "measured), json/parquet force one")
def client_predict(start, end, project, base_url, target, parquet_dir,
                   batch_size, body_encoding):
    """Bulk anomaly scoring over a time range."""
    import pandas as pd

    from gordo_components_tpu.client import Client, ForwardPredictionsIntoParquet

    forwarder = ForwardPredictionsIntoParquet(parquet_dir) if parquet_dir else None
    use_parquet = {"auto": "auto", "json": False, "parquet": True}[body_encoding]
    client = Client(
        project, base_url=base_url, forwarder=forwarder, batch_size=batch_size,
        use_parquet=use_parquet,
    )
    results = client.predict(
        pd.Timestamp(start), pd.Timestamp(end), targets=list(target) or None
    )
    ok = sum(1 for r in results if r.ok)
    click.echo(f"{ok}/{len(results)} machines scored successfully")
    for r in results:
        if not r.ok:
            click.echo(f"  FAILED {r.name}: {r.error_messages[:1]}", err=True)
    if ok < len(results):
        sys.exit(1)


@client_group.command("metadata")
@click.option("--project", envvar="PROJECT_NAME", required=True)
@click.option("--base-url", default="http://localhost:5555")
def client_metadata(project, base_url):
    """Print every model's metadata as JSON."""
    import asyncio

    import aiohttp

    from gordo_components_tpu.client.io import fetch_json, fetch_metadata_all

    async def go():
        async with aiohttp.ClientSession() as session:
            # one metadata-all request against a collection server;
            # per-target fetches only for foreign servers
            batched = await fetch_metadata_all(session, base_url, project)
            if batched is not None:
                return {
                    name: entry.get("endpoint-metadata", {})
                    for name, entry in batched["targets"].items()
                    # a catch-all proxy can pass the shape check with
                    # non-dict entries; skip them rather than crash
                    if isinstance(entry, dict)
                }
            targets = (
                await fetch_json(session, f"{base_url}/gordo/v0/{project}/models")
            )["models"]
            out = {}
            for t in targets:
                body = await fetch_json(
                    session, f"{base_url}/gordo/v0/{project}/{t}/metadata"
                )
                out[t] = body.get("endpoint-metadata", {})
            return out

    click.echo(json.dumps(asyncio.run(go()), indent=2, default=str))


@client_group.command("download-model")
@click.argument("target")
@click.argument("dest", type=click.Path())
@click.option("--project", envvar="PROJECT_NAME", required=True)
@click.option("--base-url", default="http://localhost:5555")
def client_download_model(target, dest, project, base_url):
    """Download a model artifact as a pickle file."""
    import requests

    resp = requests.get(
        f"{base_url}/gordo/v0/{project}/{target}/download-model", timeout=120
    )
    resp.raise_for_status()
    with open(dest, "wb") as f:
        f.write(resp.content)
    click.echo(dest)


@gordo.group("workflow")
def workflow_group():
    """Workflow generation."""


@workflow_group.command("compile")
@click.option("--machine-config", "-f", required=True, type=click.Path(exists=True))
@click.option("--project-name", "-p", required=True)
@click.option("--output-file", "-o", default=None, type=click.Path())
@click.option("--models-per-bucket", default=None, type=int)
@click.option("--devices-per-bucket", default=None, type=int)
def workflow_compile(machine_config, project_name, output_file,
                     models_per_bucket, devices_per_bucket):
    """Compile a fleet spec into the typed build/place/canary/promote
    DAG (deterministic JSON — the reviewed rollout artifact)."""
    from gordo_components_tpu.workflow import compile_fleet

    overrides = {}
    if models_per_bucket:
        overrides["models_per_bucket"] = models_per_bucket
    if devices_per_bucket:
        overrides["devices_per_bucket"] = devices_per_bucket
    try:
        with open(machine_config) as f:
            dag = compile_fleet(yaml.safe_load(f), project_name, **overrides)
    except (ValueError, yaml.YAMLError) as exc:
        click.echo(f"Invalid fleet spec: {exc}", err=True)
        sys.exit(EXIT_CONFIG_ERROR)
    doc = dag.to_json()
    if output_file:
        with open(output_file, "w") as f:
            f.write(doc + "\n")
        click.echo(output_file)
    else:
        click.echo(doc)


@workflow_group.command("run")
@click.option("--machine-config", "-f", required=True, type=click.Path(exists=True))
@click.option("--project-name", "-p", required=True)
@click.option("--state-dir", envvar="GORDO_FLEET_STATE_DIR",
              default=".fleet-state",
              help="Executor state (step keys, artifacts, incumbent "
                   "backups); re-runs execute only the stale subgraph")
@click.option("--server-url", envvar="SERVER_BASE_URL", default=None,
              help="Live replica to roll the fleet onto (canary + "
                   "promote through its zero-downtime /reload swap); "
                   "omitted = plan-only run (build + plan, no landing)")
@click.option("--collection-dir", envvar="MODEL_COLLECTION_DIR", default=None,
              help="The live server's artifact dir (required with "
                   "--server-url)")
@click.option("--model-register-dir", envvar="MODEL_REGISTER_DIR", default=None)
def workflow_run(machine_config, project_name, state_dir, server_url,
                 collection_dir, model_register_dir):
    """Compile AND execute a fleet spec: build -> bucket -> place ->
    canary -> promote, goodput-judged with auto-rollback."""
    from gordo_components_tpu.workflow import FleetExecutor, compile_fleet

    try:
        with open(machine_config) as f:
            dag = compile_fleet(yaml.safe_load(f), project_name)
        executor = FleetExecutor(
            dag, state_dir, server_url=server_url,
            collection_dir=collection_dir, register_dir=model_register_dir,
        )
    except (ValueError, yaml.YAMLError) as exc:
        click.echo(f"Invalid fleet spec: {exc}", err=True)
        sys.exit(EXIT_CONFIG_ERROR)
    report = executor.run()
    click.echo(json.dumps(report, indent=2, default=str))
    if report["failed"]:
        sys.exit(
            EXIT_PARTIAL_BUILD if report["executed"] else EXIT_BUILD_ERROR
        )


@workflow_group.command("generate")
@click.option("--machine-config", "-f", required=True, type=click.Path(exists=True))
@click.option("--project-name", "-p", required=True)
@click.option("--output-file", "-o", default=None, type=click.Path())
@click.option("--models-per-gang", default=None, type=int)
@click.option("--devices-per-gang", default=None, type=int)
def workflow_generate(machine_config, project_name, output_file, models_per_gang, devices_per_gang):
    """Render gang-scheduled TPU manifests from a fleet config
    (reference §3.4)."""
    from gordo_components_tpu.workflow import NormalizedConfig, generate_workflow

    try:
        config = NormalizedConfig.from_yaml_file(machine_config)
    except (ValueError, yaml.YAMLError) as exc:
        click.echo(f"Invalid machine config: {exc}", err=True)
        sys.exit(EXIT_CONFIG_ERROR)
    overrides = {}
    if models_per_gang:
        overrides["models_per_gang"] = models_per_gang
    if devices_per_gang:
        overrides["devices_per_gang"] = devices_per_gang
    try:
        # generation now compiles the spec (fleet compiler validation
        # included), so spec errors surface here too — same clean exit
        # as `workflow compile` on the identical spec
        manifest = generate_workflow(config, project_name, **overrides)
    except ValueError as exc:
        click.echo(f"Invalid fleet spec: {exc}", err=True)
        sys.exit(EXIT_CONFIG_ERROR)
    if output_file:
        with open(output_file, "w") as f:
            f.write(manifest)
        click.echo(output_file)
    else:
        click.echo(manifest)


if __name__ == "__main__":
    gordo()
