"""Watchman service.

Reference parity: gordo_components/watchman/server.py (unverified;
SURVEY.md §2 "watchman", §3.5) — the in-tree fleet failure *detector*: for a
project's target list, poll each model server's ``/healthcheck`` and
``/metadata`` and serve the aggregate
``{project_name, endpoints: [{endpoint, healthy, metadata}, ...]}``.

TPU-native notes: with the collection server, many targets share one base
URL; a snapshot costs ONE request to the batched ``metadata-all``
control-plane endpoint (with reference-style per-target polling, bounded
concurrency, as the fallback for foreign servers and for explicit targets
the collection doesn't know). Watchman discovers targets from ``GET
/models`` when no explicit list is given. Results are cached for
``refresh_interval`` seconds.
"""

import asyncio
import logging
import math
import zlib
from typing import Any, Dict, List, Optional

import aiohttp
from aiohttp import web

from gordo_components_tpu import __version__
from gordo_components_tpu.observability import (
    EventLog,
    merge_cost_snapshots,
    merge_heat_snapshots,
    merge_slo_snapshots,
    parse_prometheus_text,
    render_samples,
)
from gordo_components_tpu.watchman.correlate import (
    DEFAULT_BURN_THRESHOLD,
    burn_episodes,
    group_incidents,
)
from gordo_components_tpu.replay.clock import SYSTEM_CLOCK
from gordo_components_tpu.resilience.deadline import Deadline
from gordo_components_tpu.resilience.faults import faultpoint

logger = logging.getLogger(__name__)

# chaos sites (tests/test_chaos.py): replica /metrics scrapes and the
# health-snapshot refresh. Both degrade to last-good-with-a-stale-stamp,
# never an error — a monitoring plane that dies with what it monitors is
# worthless exactly when it matters
_FP_SCRAPE = faultpoint("watchman.scrape")
_FP_SNAPSHOT = faultpoint("watchman.snapshot")
# the watchman<->replica network seam: fires once per replica probe in
# the routing rebuild, so the transport fault kinds (reset/refuse/
# blackhole — resilience/faults.py) partition watchman from the fleet
# without touching the replicas. A fired probe reads as "replica
# unreachable" — exactly what a real partition looks like from here.
_FP_PROBE = faultpoint("watchman.probe")


def aggregate_fleet_metrics(
    texts: List[Optional[str]],
    prev_shard_rows: Optional[List[Optional[Dict[str, float]]]] = None,
) -> Dict[str, Any]:
    """Roll scraped ``/metrics`` bodies from N server replicas into one
    fleet view: per-series sums and maxes across replicas, plus the
    per-shard routing skew the per-replica counters exist to surface
    (VERDICT r5 weak #2: a hot model concentrates traffic on one shard
    while the others idle — one endpoint must answer "is any shard hot
    anywhere in the fleet"). ``texts`` is replica-aligned; ``None``
    entries mark failed scrapes.

    Skew ratio = max(shard routed rows) / mean(shard routed rows), computed
    per replica (shards of different replicas are different chips) and
    reported as the fleet max; 1.0 = perfectly balanced routing. When
    ``prev_shard_rows`` (the previous scrape's per-replica counters) is
    given, the ratio is computed on the scrape-to-scrape DELTA — lifetime
    totals from a long-lived server would bury a newly hot shard under a
    week of balanced history and never clear after a rebalance. A replica
    without a baseline (first scrape, or newly added) contributes its
    lifetime-total skew alongside the others' deltas; ``skew_window``
    records what fed the reported max ("delta", "lifetime", or
    "mixed")."""
    types: Dict[str, str] = {}
    sums: Dict[Any, float] = {}
    maxs: Dict[Any, float] = {}
    routed_by_shard: Dict[str, float] = {}
    replica_shard_rows: List[Optional[Dict[str, float]]] = []
    for text in texts:
        if text is None:
            replica_shard_rows.append(None)
            continue
        t, samples = parse_prometheus_text(text)
        types.update(t)
        shard_rows: Dict[str, float] = {}
        for name, labels, value in samples:
            if not math.isfinite(value):
                # a replica's NaN (e.g. dead read-through closure) must
                # not poison the whole fleet's sums
                continue
            key = (name, tuple(sorted(labels.items())))
            sums[key] = sums.get(key, 0.0) + value
            maxs[key] = max(maxs.get(key, value), value)
            if name == "gordo_bank_shard_routed_rows_total":
                shard = labels.get("shard", "?")
                shard_rows[shard] = shard_rows.get(shard, 0.0) + value
                routed_by_shard[shard] = routed_by_shard.get(shard, 0.0) + value
        replica_shard_rows.append(shard_rows)

    def ratio(rows: Dict[str, float]) -> Optional[float]:
        if not rows:
            return None
        mean = sum(rows.values()) / len(rows)
        return (max(rows.values()) / mean) if mean > 0 else None

    delta_skews: List[float] = []
    lifetime_skews: List[float] = []
    for idx, rows in enumerate(replica_shard_rows):
        if not rows:
            continue
        base = None
        if prev_shard_rows is not None and idx < len(prev_shard_rows):
            base = prev_shard_rows[idx]
        if base:
            deltas = {s: v - base.get(s, 0.0) for s, v in rows.items()}
            if any(d < 0 for d in deltas.values()):
                # counter reset: the replica restarted since the baseline,
                # so the baseline is void — the post-restart totals ARE
                # the recent window (a negative-delta mean would otherwise
                # report garbage ratios like 200x)
                r = ratio(rows)
                if r is not None:
                    delta_skews.append(r)
                continue
            r = ratio(deltas)
            if r is not None:
                delta_skews.append(r)
            continue  # no traffic since last scrape: no skew signal
        r = ratio(rows)
        if r is not None:
            lifetime_skews.append(r)
    # both pools count: a baseline-less replica (just added, or its first
    # scrape failed) reporting a hot shard via lifetime totals must not be
    # buried by another replica's balanced delta window
    all_skews = delta_skews + lifetime_skews
    if not all_skews:
        skew, window = None, None
    else:
        skew = max(all_skews)
        if delta_skews and lifetime_skews:
            window = "mixed"
        elif delta_skews:
            window = "delta"
        else:
            window = "lifetime"
    return {
        "replicas_scraped": sum(1 for t in texts if t is not None),
        "types": types,
        "sums": sums,
        "maxs": maxs,
        "routed_rows_by_shard": routed_by_shard,
        "replica_shard_rows": replica_shard_rows,
        "shard_skew_ratio": round(skew, 4) if skew is not None else None,
        "skew_window": window,
    }


def render_fleet_metrics(
    agg: Dict[str, Any],
    now_mono: Optional[float] = None,
    extra_gauges: Optional[List[tuple]] = None,
) -> str:
    """Aggregated rollup as Prometheus text: computed fleet gauges first,
    then the scraped series under their original names (federation-style,
    replica label collapsed). Counters and histogram samples sum across
    replicas; gauges take the replica MAX — summing uptime or an HBM
    byte limit across 8 replicas would report nonsense, while the max is
    the honest "worst/largest anywhere" fleet answer."""
    samples = [
        ("gordo_fleet_replicas_scraped", {}, float(agg["replicas_scraped"]))
    ]
    types = {"gordo_fleet_replicas_scraped": "gauge"}
    helps = {
        "gordo_fleet_replicas_scraped": "Server replicas whose /metrics answered",
        "gordo_fleet_shard_skew_ratio": (
            "max/mean routed rows across one replica's shards over the "
            "scrape-to-scrape window (lifetime totals on the first "
            "scrape), fleet max; 1.0 = balanced routing"
        ),
        "gordo_fleet_shard_routed_rows_max": "Hottest shard's routed rows",
        "gordo_fleet_shard_routed_rows_mean": "Mean routed rows per shard",
        "gordo_fleet_scrape_stale_seconds": (
            "Seconds since each replica's /metrics last answered; a "
            "missed scrape keeps the replica's last-good numbers in the "
            "rollup (counters stay monotonic) and THIS gauge is how the "
            "substitution stays visible. ~0 = fresh"
        ),
    }
    # per-replica scrape freshness, aged live at render time; a replica
    # that has NEVER answered has no last-good body to freeze and already
    # shows up via replicas_scraped, so it gets no sample here
    last_success = agg.get("replica_last_success") or []
    if any(ts is not None for ts in last_success):
        # staleness ages on the caller's clock seam (replay compresses
        # it with everything else); bare calls read the real clock
        if now_mono is None:
            now_mono = SYSTEM_CLOCK.monotonic()
        types["gordo_fleet_scrape_stale_seconds"] = "gauge"
        for i, ts in enumerate(last_success):
            if ts is None:
                continue
            samples.append(
                (
                    "gordo_fleet_scrape_stale_seconds",
                    {"replica": str(i)},
                    round(max(0.0, now_mono - ts), 3),
                )
            )
    if agg["shard_skew_ratio"] is not None:
        samples.append(
            ("gordo_fleet_shard_skew_ratio", {}, float(agg["shard_skew_ratio"]))
        )
        types["gordo_fleet_shard_skew_ratio"] = "gauge"
    # routing-plane gauges (multi-host serving): rendered only when the
    # caller passes them (a watchman that never built a table emits none)
    for name, mtype, help_text, labels, value in extra_gauges or ():
        samples.append((name, labels, float(value)))
        types[name] = mtype
        helps[name] = help_text
    shard_rows = agg["routed_rows_by_shard"]
    if shard_rows:
        vals = list(shard_rows.values())
        samples.append(("gordo_fleet_shard_routed_rows_max", {}, max(vals)))
        samples.append(
            ("gordo_fleet_shard_routed_rows_mean", {}, sum(vals) / len(vals))
        )
        types["gordo_fleet_shard_routed_rows_max"] = "gauge"
        types["gordo_fleet_shard_routed_rows_mean"] = "gauge"
    scraped_types = agg["types"]
    types.update(scraped_types)
    for (name, labelitems), value in sorted(agg["sums"].items()):
        if scraped_types.get(name) == "gauge":
            value = agg["maxs"][(name, labelitems)]
        samples.append((name, dict(labelitems), value))
    return render_samples(samples, types=types, help_texts=helps)


class WatchmanState:
    def __init__(
        self,
        project: str,
        base_url: str,
        targets: Optional[List[str]] = None,
        refresh_interval: float = 30.0,
        parallelism: int = 20,
        gang_state_dir: Optional[str] = None,
        gang_stale_after: float = 120.0,
        full_metadata: bool = False,
        metrics_urls: Optional[List[str]] = None,
        clock=None,
    ):
        self.project = project
        self.base_url = base_url.rstrip("/")
        # wall-time seam (replay/clock.py): cache ages + scrape
        # staleness read it; default is the real clock
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.targets = targets
        self.refresh_interval = refresh_interval
        self.parallelism = parallelism
        # server /metrics scrape targets for the fleet rollup; default is
        # the collection server behind base_url. Multi-replica deploys pass
        # each replica's URL so the rollup sums/maxes across all of them.
        self.metrics_urls = metrics_urls
        self._metrics_cache: Optional[Dict[str, Any]] = None
        self._metrics_time = 0.0
        self._metrics_lock = asyncio.Lock()
        # previous scrape's per-replica shard counters: the skew ratio is
        # computed on scrape-to-scrape deltas once a baseline exists
        self._metrics_prev_rows: Optional[List[Optional[Dict[str, float]]]] = None
        # last successful body per replica: a transiently failing scrape
        # substitutes its previous body so the summed counters the rollup
        # exports never DROP (Prometheus would read the dip-and-recover as
        # a counter reset and report a spurious rate() burst)
        self._metrics_last_texts: List[Optional[str]] = []
        # ...and WHEN each replica last answered (monotonic seconds): the
        # substitution must not be silent — the rollup exports
        # gordo_fleet_scrape_stale_seconds per replica so "this replica's
        # numbers are frozen" is an alertable gauge, not a mystery
        self._metrics_last_success: List[Optional[float]] = []
        self._metrics_task: Optional[asyncio.Task] = None
        # fleet_slo's half of the same last-good contract: an
        # unreachable replica's burn state must not silently vanish from
        # the merge (its budget is still burning!) — serve its last-good
        # body stamped stale/stale_seconds instead
        self._slo_last_bodies: List[Optional[Dict[str, Any]]] = []
        self._slo_last_success: List[Optional[float]] = []
        # digest polling by default (VERDICT r3 next #5): a 10k-model
        # snapshot with per-epoch training histories is tens of MB of JSON
        # encoded on the SERVING process every refresh; the digest keeps
        # the control plane O(small) bytes. full_metadata restores the
        # reference-style full aggregate on request.
        self.full_metadata = bool(full_metadata)
        # builder-side failure detection: aggregate gang heartbeats from
        # the shared state volume (workflow/gang_state.py) so a stalled or
        # failed TPU gang is visible next to serving health
        self.gang_state_dir = gang_state_dir
        self.gang_stale_after = gang_stale_after
        self._cache: Optional[Dict[str, Any]] = None
        self._cache_time = 0.0
        self._lock = asyncio.Lock()
        # streaming drift rollup cache (fleet_drift): refreshed on the
        # snapshot cadence; staleness above this folds into the health
        # snapshot's degraded calculus (env GORDO_STALENESS_DEGRADED_S)
        from gordo_components_tpu.utils import env_num

        self.staleness_degraded_s = env_num(
            "GORDO_STALENESS_DEGRADED_S", 600.0, float
        )
        self._drift_cache: Optional[Dict[str, Any]] = None
        self._drift_time = 0.0
        self._drift_lock = asyncio.Lock()
        self._drift_task: Optional[asyncio.Task] = None
        # --- routing/membership plane (multi-host serving mesh) ---
        # versioned member -> replica table, built from each replica's
        # /models (its live ownership truth) + /healthz; the version
        # bumps ONLY when table content changes, so clients cache on the
        # ETag and a rebalance is detectable as a version step
        self._routing_cache: Optional[Dict[str, Any]] = None
        self._routing_time = 0.0
        self._routing_version = 0
        self._routing_core: Optional[Any] = None  # comparable content key
        self._routing_lock = asyncio.Lock()
        self._routing_task: Optional[asyncio.Task] = None
        # migration pins: member -> destination replica, set the moment a
        # move's acquire lands so routing flips BEFORE the source
        # releases (the zero-404 ordering); dropped once observation
        # confirms single ownership at the destination
        self._routing_overrides: Dict[str, int] = {}
        # last observed reachability per replica index: a True->False
        # transition (replica went dark) FORCES a version bump and emits
        # mesh.replica_unreachable, so partition-aware clients poll their
        # way off dead owners even if the table content were to compare
        # equal (and the incident timeline gets the causal edge)
        self._replica_reachable: Dict[int, bool] = {}
        # per-replica full member lists from the last routing refresh
        # (fleet-planner input; deliberately NOT in the GET /routing body
        # — the members map already carries the full assignment once)
        self._routing_member_lists: Dict[int, List[str]] = {}
        self._migrations_total = 0
        self._migrations_failed = 0
        # moves serialize: two concurrent migrations of one member (or
        # interleaved acquire/release on one replica) is how routing
        # truth forks
        self._migration_lock = asyncio.Lock()
        self.mesh_min_rows = int(
            env_num("GORDO_MESH_MIN_ROWS", 1024.0, float)
        )
        # watchman's own slice of the fleet timeline: control-plane
        # transitions it performs itself (migrations) land here and
        # merge into GET /events and /incidents next to replica events
        self.events = EventLog(clock=self.clock, replica="watchman")

    def _url(self, target: str, endpoint: str) -> str:
        return f"{self.base_url}/gordo/v0/{self.project}/{target}/{endpoint}"

    async def _check_target(self, session, sem, target: str) -> Dict[str, Any]:
        entry: Dict[str, Any] = {
            "endpoint": f"/gordo/v0/{self.project}/{target}/",
            "target": target,
            "healthy": False,
        }
        async with sem:
            try:
                async with session.get(self._url(target, "healthcheck")) as resp:
                    entry["healthy"] = resp.status == 200
                if entry["healthy"]:
                    async with session.get(self._url(target, "metadata")) as resp:
                        if resp.status == 200:
                            body = await resp.json()
                            meta = body.get("endpoint-metadata", {})
                            if self.full_metadata:
                                entry["endpoint-metadata"] = meta
                            else:
                                # foreign servers only speak full metadata;
                                # digest locally so the snapshot shape is
                                # uniform across the batched and fallback
                                # paths
                                from gordo_components_tpu.utils.digest import (
                                    metadata_digest,
                                )

                                entry["digest"] = metadata_digest(meta)
            except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
                logger.warning("healthcheck failed for %s: %s", target, exc)
        return entry

    async def _fetch_metadata_all(self, session) -> Optional[Dict[str, Any]]:
        """The collection server's batched control-plane endpoint: every
        target's health + metadata in ONE request (O(1) per snapshot
        instead of O(2N) per-target polls hammering the process that also
        serves scoring traffic). Returns None when the server doesn't
        speak it, so foreign per-model servers keep working via the
        per-target fallback (shared deadline + shape-validation contract:
        client/io.py::fetch_metadata_all)."""
        from gordo_components_tpu.client.io import fetch_metadata_all

        return await fetch_metadata_all(
            session, self.base_url, self.project,
            digest=not self.full_metadata,
        )

    async def _fetch_stats(self, session) -> Optional[Dict[str, Any]]:
        """Serving-load counters from the collection's ``/stats`` — a
        best-effort decoration (collection servers only; foreign servers
        simply lack it) so operators see request/coalescing load next to
        fleet health."""

        async def get():
            async with session.get(
                f"{self.base_url}/gordo/v0/{self.project}/stats"
            ) as resp:
                if resp.status != 200:
                    return None
                return await resp.json()

        try:
            # shared deadline helper (resilience/deadline.py) — the same
            # bound the client transport uses; DeadlineExceeded
            # subclasses asyncio.TimeoutError so the catch stays one line
            body = await Deadline(10.0).wait_for(get())
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError) as exc:
            logger.debug("stats fetch failed: %s", exc)
            return None
        return body if isinstance(body, dict) else None

    async def fleet_metrics(self, wait: bool = True) -> Optional[Dict[str, Any]]:
        """Fleet-wide metrics rollup: scrape every server's ``/metrics``
        and aggregate (sum/max across replicas, per-shard skew ratio over
        the scrape-to-scrape window). Cached for ``refresh_interval`` like
        the health snapshot; scrape failures degrade to a smaller replica
        count, never an error — foreign servers without ``/metrics``
        simply contribute nothing.

        ``wait=False`` (the health snapshot path) NEVER blocks on a
        scrape: it returns the cached rollup (possibly stale, possibly
        None on a fresh process) and kicks a background refresh — a hung
        replica must not add its 10s scrape timeout to the `/` health
        endpoint."""
        if not wait:
            if (
                self._metrics_cache is None
                or self.clock.monotonic() - self._metrics_time >= self.refresh_interval
            ) and (self._metrics_task is None or self._metrics_task.done()):
                self._metrics_task = asyncio.get_running_loop().create_task(
                    self.fleet_metrics()
                )
            return self._metrics_cache
        async with self._metrics_lock:
            now = self.clock.monotonic()
            if (
                self._metrics_cache is not None
                and now - self._metrics_time < self.refresh_interval
            ):
                return self._metrics_cache
            urls = self.metrics_urls or [
                f"{self.base_url}/gordo/v0/{self.project}/metrics"
            ]
            timeout = aiohttp.ClientTimeout(total=30)
            async with aiohttp.ClientSession(timeout=timeout) as session:

                async def scrape(url):
                    async def get():
                        async with session.get(url) as resp:
                            if resp.status != 200:
                                return None
                            return await resp.text()

                    try:
                        _FP_SCRAPE.fire()
                        return await Deadline(10.0).wait_for(get())
                    except asyncio.CancelledError:
                        raise
                    except Exception as exc:
                        # broad by contract ("scrape failures degrade,
                        # never an error"): a foreign peer can 200 with
                        # garbage bytes (UnicodeDecodeError), not just
                        # fail with ClientError/Timeout
                        logger.debug("metrics scrape failed for %s: %s", url, exc)
                        return None

                texts = list(
                    await asyncio.gather(*(scrape(u) for u in urls))
                )
            live_count = sum(1 for t in texts if t is not None)
            # per-replica freshness BEFORE the last-good substitution: a
            # replica serving frozen numbers is stale, not live
            mono = self.clock.monotonic()
            succ = self._metrics_last_success
            succ.extend([None] * (len(texts) - len(succ)))
            for i, t in enumerate(texts):
                if t is not None:
                    succ[i] = mono
            # freeze failed replicas at their last successful body: summed
            # counters must stay monotonic across a transient scrape miss
            last = self._metrics_last_texts
            texts = [
                t if t is not None else (last[i] if i < len(last) else None)
                for i, t in enumerate(texts)
            ]
            self._metrics_last_texts = texts
            self._metrics_cache = aggregate_fleet_metrics(
                texts, prev_shard_rows=self._metrics_prev_rows
            )
            # report LIVE replicas, not stale substitutions — the operator
            # signal "a replica stopped answering" must survive freezing
            self._metrics_cache["replicas_scraped"] = live_count
            # monotonic last-answer times ride in the aggregate so the
            # exposition computes LIVE staleness at render time (a rollup
            # served from cache between scrapes keeps aging honestly)
            self._metrics_cache["replica_last_success"] = list(succ)
            # next scrape's delta baseline: keep the last non-None rows
            # per replica so a transient scrape failure doesn't reset the
            # window to lifetime
            new_rows = self._metrics_cache["replica_shard_rows"]
            prev = self._metrics_prev_rows or [None] * len(new_rows)
            self._metrics_prev_rows = [
                n if n is not None else (prev[i] if i < len(prev) else None)
                for i, n in enumerate(new_rows)
            ]
            self._metrics_time = now
            return self._metrics_cache

    def _trace_urls(self) -> List[str]:
        """Per-replica slow-trace endpoints, derived from the metrics
        scrape targets (same replica set, sibling path)."""
        return [u + "/traces/slow" for u in self._replica_prefixes()]

    async def fleet_slo(self, refresh: bool = False) -> Dict[str, Any]:
        """Fleet SLO rollup: fetch every replica's ``GET /slo`` and merge
        (observability/slo.py::merge_slo_snapshots) — good/total deltas
        sum per (objective, window), fleet burn rates recompute from the
        summed ratios, and ``worst_burn`` names the replica index burning
        hottest. Best-effort like the trace view: a replica that fails to
        answer is marked unscraped, never an error. ``refresh`` forwards
        ``?refresh=1`` so every replica forces a fresh sample first."""
        urls = [u + "/slo" for u in self._replica_prefixes()]
        params = {"refresh": "1"} if refresh else None
        timeout = aiohttp.ClientTimeout(total=30)
        async with aiohttp.ClientSession(timeout=timeout) as session:

            async def fetch(url):
                async def get():
                    async with session.get(url, params=params) as resp:
                        if resp.status != 200:
                            return None
                        return await resp.json()

                try:
                    return await Deadline(10.0).wait_for(get())
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.debug("slo scrape failed for %s: %s", url, exc)
                    return None

            bodies = list(await asyncio.gather(*(fetch(u) for u in urls)))
        # last-good substitution (the /metrics rollup's contract, applied
        # to /slo): an unreachable replica keeps contributing its last
        # successful body — frozen burn state beats a silent vanish from
        # the fleet sums — stamped stale/stale_seconds so the
        # substitution is an alertable signal, never a mystery
        live = [body is not None for body in bodies]
        mono = self.clock.monotonic()
        succ = self._slo_last_success
        succ.extend([None] * (len(bodies) - len(succ)))
        for i, body in enumerate(bodies):
            if body is not None:
                succ[i] = mono
        last = self._slo_last_bodies
        bodies = [
            b if b is not None else (last[i] if i < len(last) else None)
            for i, b in enumerate(bodies)
        ]
        self._slo_last_bodies = bodies
        merged = merge_slo_snapshots(bodies)
        merged["replicas"] = [
            {
                "replica": i,
                "scraped": live[i],
                "stale": body is not None and not live[i],
                "stale_seconds": (
                    round(mono - succ[i], 3)
                    if not live[i] and succ[i] is not None
                    else None
                ),
                "slo_enabled": bool(body and body.get("enabled")),
                "worst": (body or {}).get("worst"),
            }
            for i, body in enumerate(bodies)
        ]
        merged["replicas_scraped"] = sum(live)
        return merged

    async def fleet_heat(
        self, top_n: int = 10, refresh: bool = False
    ) -> Dict[str, Any]:
        """Fleet access-heat rollup: every replica's ``GET /heat``
        merged (observability/heat.py::merge_heat_snapshots) — per-
        member rates SUM across replicas and re-rank into ONE fleet
        hottest/coldest list (the ranked list a tiered bank or the
        placement planner reads), tier counts and per-bucket breakdowns
        sum per tier. Best-effort: an unanswering replica is counted
        out, never an error. ``refresh`` forces a fold on every replica
        first; ``top_n`` forwards as each replica's ``?top=``."""
        params: Dict[int, Any] = {}
        n = len(self._replica_prefixes())
        q = {"top": str(int(top_n))}
        if refresh:
            q["refresh"] = "1"
        for i in range(n):
            params[i] = q
        bodies = await self._fetch_replica_json("heat", params)
        merged = merge_heat_snapshots(bodies, top_n=top_n)
        merged["replicas"] = [
            {
                "replica": i,
                "scraped": body is not None,
                "heat_enabled": bool(body and body.get("enabled")),
            }
            for i, body in enumerate(bodies)
        ]
        return merged

    async def fleet_costs(self, refresh: bool = False) -> Dict[str, Any]:
        """Fleet device-cost rollup: every replica's ``GET /costs``
        merged (observability/cost.py::merge_cost_snapshots) — raw
        row/second tallies sum per bucket label, derived MFU/waste
        fields recompute through the same arithmetic the replicas used
        (no-drift), and the ranking re-orders fleet-wide."""
        params: Dict[int, Any] = {}
        if refresh:
            n = len(self._replica_prefixes())
            for i in range(n):
                params[i] = {"refresh": "1"}
        bodies = await self._fetch_replica_json(
            "costs", params if refresh else None
        )
        merged = merge_cost_snapshots(bodies)
        merged["replicas"] = [
            {
                "replica": i,
                "scraped": body is not None,
                "cost_enabled": bool(body and body.get("enabled")),
            }
            for i, body in enumerate(bodies)
        ]
        return merged

    # ------------------------------------------------------------------ #
    # fleet flight recorder: history + events rollups, incident join
    # ------------------------------------------------------------------ #

    async def _fetch_replica_json(
        self, suffix: str, params_per_replica=None
    ) -> List[Optional[Dict[str, Any]]]:
        """Best-effort ``GET <replica>/<suffix>`` across the fleet: one
        body (or None) per replica, in replica order.
        ``params_per_replica`` maps replica index -> query params; an
        index with params ``False`` is skipped (stays None)."""
        prefixes = self._replica_prefixes()
        timeout = aiohttp.ClientTimeout(total=30)
        async with aiohttp.ClientSession(timeout=timeout) as session:

            async def fetch(i, url):
                params = (
                    params_per_replica.get(i)
                    if params_per_replica is not None
                    else None
                )
                if params is False:
                    return None

                async def get():
                    async with session.get(url, params=params) as resp:
                        if resp.status != 200:
                            return None
                        return await resp.json()

                try:
                    return await Deadline(10.0).wait_for(get())
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.debug("%s fetch failed for %s: %s", suffix, url, exc)
                    return None

            return list(
                await asyncio.gather(
                    *(
                        fetch(i, f"{p}/{suffix}")
                        for i, p in enumerate(prefixes)
                    )
                )
            )

    async def fleet_history(
        self,
        series: Optional[List[str]] = None,
        since: Optional[float] = None,
        until: Optional[float] = None,
        step: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Fleet history rollup: every replica's ``GET /history`` body,
        per replica (series stay attributed to the replica that
        recorded them — summing retained gauges across replicas would
        manufacture numbers nobody measured). Replicas with history
        disabled answer ``enabled: false`` and contribute nothing."""
        params: Dict[str, str] = {}
        if series:
            params["series"] = ",".join(series)
        for key, val in (("since", since), ("until", until), ("step", step)):
            if val is not None:
                params[key] = str(val)
        shared = {i: (params or None) for i in range(len(self._replica_prefixes()))}
        bodies = await self._fetch_replica_json("history", shared)
        return {
            "replicas_scraped": sum(1 for b in bodies if b is not None),
            "replicas": [
                {
                    "replica": i,
                    "scraped": b is not None,
                    **(b if b is not None else {"enabled": False}),
                }
                for i, b in enumerate(bodies)
            ],
        }

    async def fleet_events(
        self,
        since_wall: Optional[float] = None,
        types: Optional[List[str]] = None,
        limit: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Fleet event rollup: every replica's ``GET /events`` merged
        with watchman's own control-plane log (migrations), ordered by
        wall time. Each event gains ``replica_index``; events a replica
        emitted without a replica name get ``replica-<i>``."""
        params: Dict[str, str] = {}
        if since_wall is not None:
            params["since_wall"] = str(since_wall)
        if types:
            params["type"] = ",".join(types)
        shared = {i: (params or None) for i in range(len(self._replica_prefixes()))}
        bodies = await self._fetch_replica_json("events", shared)
        merged: List[Dict[str, Any]] = []
        for i, body in enumerate(bodies):
            for ev in (body or {}).get("events") or ():
                ev = dict(ev, replica_index=i)
                if not ev.get("replica"):
                    ev["replica"] = f"replica-{i}"
                merged.append(ev)
        merged.extend(
            self.events.events(types=types, since_wall=since_wall)
        )
        merged.sort(
            key=lambda ev: (float(ev.get("wall", 0)), ev.get("seq", 0))
        )
        if limit is not None and limit >= 0:
            merged = merged[-limit:]
        return {
            "replicas_scraped": sum(1 for b in bodies if b is not None),
            "events": merged,
        }

    async def fleet_incidents(
        self,
        threshold: Optional[float] = None,
        margin_s: Optional[float] = None,
        min_points: int = 1,
    ) -> Dict[str, Any]:
        """The flight-recorder join (watchman/correlate.py): find every
        replica's SLO-burn episodes in its retained
        ``gordo_slo_burn_rate`` history, group overlapping episodes
        fleet-wide into incidents, and attach the fleet event timeline
        that overlaps each one. Needs ``GORDO_HISTORY=1`` on the
        replicas — without it there is no retained burn series and the
        body says so instead of detecting nothing silently."""
        thr = DEFAULT_BURN_THRESHOLD if threshold is None else float(threshold)
        margin = 30.0 if margin_s is None else float(margin_s)
        metas = await self._fetch_replica_json("history")
        wanted: Dict[int, Any] = {}
        for i, meta in enumerate(metas):
            has_burn = any(
                n.startswith("gordo_slo_burn_rate")
                for n in ((meta or {}).get("names") or ())
            )
            # the base name expands server-side to every retained
            # objective/window label set (full keys contain commas)
            wanted[i] = {"series": "gordo_slo_burn_rate"} if has_burn else False
        history_enabled = sum(
            1 for m in metas if m is not None and m.get("enabled")
        )
        episodes: List[Dict[str, Any]] = []
        if any(p is not False for p in wanted.values()):
            bodies = await self._fetch_replica_json("history", wanted)
            for i, body in enumerate(bodies):
                for name, rec in ((body or {}).get("series") or {}).items():
                    for ep in burn_episodes(
                        rec.get("points") or (), thr, min_points
                    ):
                        ep["series"] = name
                        ep["replica"] = i
                        episodes.append(ep)
        events_body = await self.fleet_events()
        incidents = group_incidents(episodes, events_body["events"], margin)
        return {
            "incidents": incidents,
            "detected": len(incidents),
            "episodes": len(episodes),
            "threshold": thr,
            "margin_s": margin,
            "replicas_with_history": history_enabled,
            "replicas_scraped": events_body["replicas_scraped"],
        }

    async def fleet_drift(
        self, refresh: bool = False, wait: bool = True
    ) -> Optional[Dict[str, Any]]:
        """Fleet drift rollup (streaming adaptation plane): fetch every
        replica's ``GET /drift`` and aggregate — per replica the drifted
        member list, the WORST-drift member attribution, and the max
        staleness; fleet-wide the union of drifted members, the worst
        (replica, member, score) triple, and the max
        ``gordo_model_staleness_seconds``. Replicas with streaming
        disabled (or unreachable) contribute nothing, never an error.

        ``wait=False`` (the health-snapshot path) serves the cached
        rollup and kicks a background refresh — one hung replica must
        not add its scrape timeout to the ``/`` health endpoint.
        ``refresh`` forwards ``?refresh=1`` so every replica runs a
        fresh drift sweep first."""
        if not wait:
            if (
                self._drift_cache is None
                or self.clock.monotonic() - self._drift_time >= self.refresh_interval
            ) and (self._drift_task is None or self._drift_task.done()):
                self._drift_task = asyncio.get_running_loop().create_task(
                    self.fleet_drift()
                )
            return self._drift_cache
        async with self._drift_lock:
            now = self.clock.monotonic()
            if (
                not refresh
                and self._drift_cache is not None
                and now - self._drift_time < self.refresh_interval
            ):
                return self._drift_cache
            urls = [u + "/drift" for u in self._replica_prefixes()]
            params = {"refresh": "1"} if refresh else None
            timeout = aiohttp.ClientTimeout(total=30)
            async with aiohttp.ClientSession(timeout=timeout) as session:

                async def fetch(url):
                    async def get():
                        async with session.get(url, params=params) as resp:
                            if resp.status != 200:
                                return None
                            return await resp.json()

                    try:
                        return await Deadline(10.0).wait_for(get())
                    except asyncio.CancelledError:
                        raise
                    except Exception as exc:
                        logger.debug("drift scrape failed for %s: %s", url, exc)
                        return None

                bodies = list(await asyncio.gather(*(fetch(u) for u in urls)))
            replicas: List[Dict[str, Any]] = []
            drifted_union: List[str] = []
            worst: Optional[Dict[str, Any]] = None
            max_stale: Optional[float] = None
            for i, body in enumerate(bodies):
                entry: Dict[str, Any] = {
                    "replica": i,
                    "scraped": body is not None,
                    "stream_enabled": bool(body and body.get("enabled")),
                }
                if body and body.get("enabled"):
                    drifted = body.get("drifted") or []
                    entry["drifted"] = drifted
                    drifted_union.extend(drifted)
                    members = body.get("members") or {}
                    r_worst, r_stale = None, None
                    for name, m in members.items():
                        score = m.get("drift_score")
                        if score is not None and (
                            r_worst is None or score > r_worst["drift_score"]
                        ):
                            r_worst = {"model": name, "drift_score": score}
                        stale = m.get("staleness_seconds")
                        if stale is not None and (
                            r_stale is None or stale > r_stale
                        ):
                            r_stale = stale
                    entry["worst"] = r_worst
                    entry["max_staleness_seconds"] = r_stale
                    if r_worst is not None and (
                        worst is None
                        or r_worst["drift_score"] > worst["drift_score"]
                    ):
                        worst = {"replica": i, **r_worst}
                    if r_stale is not None and (
                        max_stale is None or r_stale > max_stale
                    ):
                        max_stale = r_stale
                replicas.append(entry)
            rollup = {
                "replicas": replicas,
                "replicas_streaming": sum(
                    1 for r in replicas if r["stream_enabled"]
                ),
                "drifted": sorted(set(drifted_union)),
                "worst": worst,
                "max_staleness_seconds": max_stale,
                "staleness_degraded_s": self.staleness_degraded_s,
                "stale_degraded": bool(
                    max_stale is not None
                    and max_stale > self.staleness_degraded_s
                ),
            }
            self._drift_cache = rollup
            self._drift_time = self.clock.monotonic()
            return rollup

    async def fleet_rebalance(
        self, dry_run: bool = False, force: bool = False
    ) -> Dict[str, Any]:
        """Fleet rebalance fan-out (placement control plane): POST every
        replica's ``/rebalance`` (or preview with ``dry_run``) and
        report per-replica verdicts — watchman as the fleet's placement
        controller for deploys that run it instead of the in-server
        ``GORDO_REBALANCE=auto`` loop. Best-effort per replica: one
        replica's failed swap (it rolled back and keeps serving its old
        generation) must not abort the others' rebalances."""
        urls = [u + "/rebalance" for u in self._replica_prefixes()]
        params = {"dry_run": "1"} if dry_run else None
        payload = {"force": True} if force else {}
        timeout = aiohttp.ClientTimeout(total=300)
        async with aiohttp.ClientSession(timeout=timeout) as session:

            async def post(url):
                async def go():
                    async with session.post(
                        url, params=params, json=payload
                    ) as resp:
                        return resp.status, await resp.json()

                try:
                    # generous bound: an applied swap pays a bank build +
                    # warm compile before it answers (the flip itself is
                    # sub-millisecond; see the swap-pause histogram)
                    return await Deadline(240.0).wait_for(go())
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.warning("rebalance failed for %s: %s", url, exc)
                    return None, {"error": f"{type(exc).__name__}: {exc}"}

            results = list(await asyncio.gather(*(post(u) for u in urls)))
        replicas = []
        for i, (status, body) in enumerate(results):
            body = body if isinstance(body, dict) else {}
            replicas.append(
                {
                    "replica": i,
                    "reached": status is not None,
                    "status": status,
                    "applied": bool(body.get("applied")),
                    "rolled_back": bool(body.get("rolled_back")),
                    "generation": (body.get("swap") or {}).get(
                        "generation", body.get("generation")
                    ),
                    "reason": (body.get("plan") or {}).get("reason")
                    or body.get("error"),
                }
            )
        return {
            "dry_run": dry_run,
            "force": force,
            "applied": sum(1 for r in replicas if r["applied"]),
            "replicas": replicas,
        }

    def _replica_prefixes(self) -> List[str]:
        """Per-replica ``.../gordo/v0/<project>`` prefixes, derived from
        the metrics scrape targets (the authoritative replica set)."""
        urls = self.metrics_urls or [
            f"{self.base_url}/gordo/v0/{self.project}/metrics"
        ]
        suffix = "/metrics"
        out = []
        for u in urls:
            u = u.rstrip("/")  # tolerate a trailing slash on the target
            if u.endswith(suffix):
                u = u[: -len(suffix)]
            out.append(u)
        return out

    def replica_base_urls(self) -> List[str]:
        """Replica BASE URLs (scheme://host:port), served in the health
        snapshot as the fleet's target list — the bulk client's hedging
        mode picks its second replica from exactly this list
        (``Client.replicas_from_watchman``), so "which replicas exist"
        has one owner."""
        marker = "/gordo/v0/"
        out: List[str] = []
        for u in self._replica_prefixes():
            base = u.split(marker, 1)[0] if marker in u else u
            if base and base not in out:
                out.append(base)
        return out

    # ------------------------------------------------------------------ #
    # routing/membership plane (multi-host serving mesh)
    # ------------------------------------------------------------------ #

    @staticmethod
    async def _get_json(session, url: str, deadline: float = 10.0):
        """Bounded best-effort JSON GET for the routing plane: None on
        any failure (an unreachable replica is a table entry, never an
        exception). Non-2xx bodies that still parse are RETURNED — a
        503 /healthz body carries the status we need."""

        async def get():
            async with session.get(url) as resp:
                try:
                    return await resp.json()
                except Exception:
                    return None

        try:
            return await Deadline(deadline).wait_for(get())
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            logger.debug("routing fetch failed for %s: %s", url, exc)
            return None

    async def routing(
        self, refresh: bool = False, wait: bool = True
    ) -> Optional[Dict[str, Any]]:
        """The versioned routing table: member -> owning replica, plus
        per-replica health the client's hedging consults. Built by
        fetching every replica's ``/models`` (live ownership truth: the
        collection behind it is exactly what answers scoring requests)
        and ``/healthz`` (ok/degraded/unhealthy + the quarantined set).

        Versioning rule: the version bumps IFF the table's content
        (ownership, reachability, health status, quarantine sets)
        changed since the last build — a quiet fleet re-observed keeps
        its version, so ``ETag``-conditional polls are free. Members
        observed on several replicas mid-migration resolve to the
        pinned override (the move's destination) when one is active,
        else the lowest replica index, and are listed under
        ``migrating`` so operators can watch the overlap window close.

        ``wait=False`` (the health-snapshot path) serves the cache and
        kicks a background refresh — the ``/`` endpoint never inherits
        a dead replica's fetch timeout."""
        if not wait:
            if (
                self._routing_cache is None
                or self.clock.monotonic() - self._routing_time
                >= self.refresh_interval
            ) and (self._routing_task is None or self._routing_task.done()):
                self._routing_task = asyncio.get_running_loop().create_task(
                    self.routing()
                )
            return self._stamped_routing()
        async with self._routing_lock:
            now = self.clock.monotonic()
            if (
                not refresh
                and self._routing_cache is not None
                and now - self._routing_time < self.refresh_interval
            ):
                return self._stamped_routing()
            prefixes = self._replica_prefixes()
            # base per PREFIX, not via replica_base_urls(): that list is
            # deduplicated, so two scrape targets sharing a host would
            # shift every later replica's index and stamp replica i with
            # replica j's url/health
            marker = "/gordo/v0/"
            bases = [
                p.split(marker, 1)[0] if marker in p else p for p in prefixes
            ]
            timeout = aiohttp.ClientTimeout(total=30)
            async with aiohttp.ClientSession(timeout=timeout) as session:

                async def probe(i: int, prefix: str):
                    try:
                        _FP_PROBE.fire()
                    except asyncio.CancelledError:
                        raise
                    except Exception as exc:
                        # an injected partition: this replica is dark
                        # from watchman's side of the network this round
                        logger.debug(
                            "routing probe chaos for %s: %s", prefix, exc
                        )
                        return i, None, None
                    models, health = await asyncio.gather(
                        self._get_json(session, prefix + "/models"),
                        self._get_json(session, prefix + "/healthz"),
                    )
                    return i, models, health

                results = await asyncio.gather(
                    *(probe(i, p) for i, p in enumerate(prefixes))
                )
            replicas: List[Dict[str, Any]] = []
            observed: Dict[str, List[int]] = {}
            member_lists: Dict[int, List[str]] = {}
            for i, models_body, health_body in results:
                base = bases[i]
                names = []
                reachable = False
                if isinstance(models_body, dict) and isinstance(
                    models_body.get("models"), list
                ):
                    reachable = True
                    names = [str(n) for n in models_body["models"]]
                status = "unreachable"
                quarantined: List[str] = []
                if isinstance(health_body, dict) and health_body.get("status"):
                    status = str(health_body["status"])
                    quarantined = sorted(health_body.get("quarantined") or {})
                elif reachable:
                    # /models answered but /healthz didn't (foreign
                    # server): servable, health unknown
                    status = "ok"
                member_lists[i] = names
                for name in names:
                    observed.setdefault(name, []).append(i)
                replicas.append(
                    {
                        "replica": i,
                        "url": base,
                        "reachable": reachable,
                        "status": status,
                        "models": len(names),
                        "quarantined": quarantined,
                    }
                )
            members: Dict[str, int] = {}
            migrating: Dict[str, List[int]] = {}
            for name, owners in observed.items():
                override = self._routing_overrides.get(name)
                if override is not None and override in owners:
                    members[name] = override
                    if len(owners) == 1:
                        # migration converged at the destination: unpin
                        del self._routing_overrides[name]
                else:
                    if override is not None:
                        # destination lost (or never gained) the member:
                        # observation wins, the pin is void
                        del self._routing_overrides[name]
                    # multi-owned with no pin (a fully REPLICATED fleet,
                    # or a dual-owner overlap nobody is driving): spread
                    # primaries deterministically by name hash — "lowest
                    # index wins" would route every member of a
                    # replicated fleet to replica 0 and idle the rest
                    owners_sorted = sorted(owners)
                    members[name] = owners_sorted[
                        zlib.crc32(name.encode()) % len(owners_sorted)
                    ]
                if len(owners) > 1:
                    migrating[name] = sorted(owners)
            # drop pins for members that vanished entirely
            for name in list(self._routing_overrides):
                if name not in observed:
                    del self._routing_overrides[name]
            # reachability transitions: a replica going dark is a routing
            # event in its own right — the version MUST step (clients
            # ETag-poll off the dead owner) and the fleet timeline gets
            # the edge the incident correlator orders against SLO burn
            went_dark: List[Dict[str, Any]] = []
            came_back: List[Dict[str, Any]] = []
            for rep in replicas:
                prev = self._replica_reachable.get(rep["replica"])
                if prev is True and not rep["reachable"]:
                    went_dark.append(rep)
                elif prev is False and rep["reachable"]:
                    came_back.append(rep)
                self._replica_reachable[rep["replica"]] = rep["reachable"]
            core = self._routing_content_key(members, replicas, migrating)
            if core != self._routing_core:
                self._routing_version += 1
                self._routing_core = core
            elif went_dark:
                # belt-and-braces: the content key already covers the
                # reachable flag, but the unreachable transition is the
                # one case where serving a stale version means routing
                # scoring traffic at a corpse — bump unconditionally
                self._routing_version += 1
            for rep in went_dark:
                self.events.emit(
                    "mesh.replica_unreachable",
                    severity="error",
                    replica_index=rep["replica"],
                    url=rep["url"],
                    routing_version=self._routing_version,
                )
            for rep in came_back:
                self.events.emit(
                    "mesh.replica_recovered",
                    severity="info",
                    replica_index=rep["replica"],
                    url=rep["url"],
                    routing_version=self._routing_version,
                )
            self._routing_member_lists = member_lists
            self._routing_cache = {
                "project": self.project,
                "version": self._routing_version,
                "members": members,
                "migrating": migrating,
                "replicas": replicas,
                "refresh_interval": self.refresh_interval,
            }
            self._routing_time = self.clock.monotonic()
            return self._stamped_routing()

    @staticmethod
    def _routing_content_key(members, replicas, migrating) -> tuple:
        """The comparable content of a routing table: the version bumps
        IFF this changes (the ETag contract's definition of 'changed')."""
        return (
            tuple(sorted(members.items())),
            tuple(
                (r["replica"], r["url"], r["reachable"], r["status"],
                 tuple(r["quarantined"]))
                for r in replicas
            ),
            tuple(sorted((k, tuple(v)) for k, v in migrating.items())),
        )

    def _stamped_routing(self) -> Optional[Dict[str, Any]]:
        """The cached table with a LIVE age stamp — staleness must keep
        aging between refreshes, so a client can tell 'fresh table' from
        'watchman stopped observing' without comparing clocks."""
        if self._routing_cache is None:
            return None
        age = max(0.0, self.clock.monotonic() - self._routing_time)
        body = dict(self._routing_cache)
        body["age_s"] = round(age, 3)
        body["stale"] = age >= 2 * self.refresh_interval
        return body

    def _bump_routing_owner(self, member: str, dst: int) -> None:
        """Flip a member's owner in the LIVE table (called between a
        move's acquire and release): the table must route to the
        destination before the source stops answering. Bumps the
        version — this IS a content change."""
        self._routing_overrides[member] = dst
        if self._routing_cache is not None:
            members = dict(self._routing_cache["members"])
            if members.get(member) != dst:
                members[member] = dst
                self._routing_version += 1
                # recompute the content key from the FLIPPED table: a
                # clean migration then costs exactly ONE version bump —
                # the post-release rebuild (same members, overlap closed)
                # compares equal and keeps the version, so ETag pollers
                # never refetch a byte-identical table
                self._routing_core = self._routing_content_key(
                    members,
                    self._routing_cache["replicas"],
                    self._routing_cache["migrating"],
                )
                self._routing_cache = {
                    **self._routing_cache,
                    "members": members,
                    "version": self._routing_version,
                }

    async def _replica_health_for_moves(self) -> Dict[int, str]:
        """Destination-eligibility map for the fleet planner: the routing
        table's per-replica status, escalated to ``burning`` when the
        replica's SLO rollup shows a fast burn (PR 7's signal) — a
        replica paying down an error budget must not be handed MORE
        members, even if its /healthz still says ok."""
        table = await self.routing()
        health: Dict[int, str] = {}
        for rep in (table or {}).get("replicas", []):
            health[rep["replica"]] = (
                rep["status"] if rep["reachable"] else "unreachable"
            )
        try:
            slo = await self.fleet_slo()
        except Exception:
            return health
        for entry in slo.get("replicas", []):
            worst = entry.get("worst") or {}
            if isinstance(worst, dict) and worst.get("fast_burn"):
                health[entry["replica"]] = "burning"
        return health

    async def fleet_loads(self) -> Dict[str, float]:
        """Fleet-rolled per-member routed rows over each replica's
        decision window: every replica's ``GET /placement``
        ``member_rows`` summed by member (a member normally lives on one
        replica; mid-migration both sides' windows count — the member
        really did route that much). The fleet planner's load signal."""
        urls = [p + "/placement" for p in self._replica_prefixes()]
        timeout = aiohttp.ClientTimeout(total=30)
        loads: Dict[str, float] = {}
        async with aiohttp.ClientSession(timeout=timeout) as session:
            bodies = await asyncio.gather(
                *(self._get_json(session, u) for u in urls)
            )
        for body in bodies:
            if not isinstance(body, dict):
                continue
            for name, rows in (body.get("member_rows") or {}).items():
                try:
                    loads[name] = loads.get(name, 0.0) + float(rows)
                except (TypeError, ValueError):
                    continue
        return loads

    async def apply_move(
        self,
        member: str,
        dst: int,
        src: Optional[int] = None,
    ) -> Dict[str, Any]:
        """One cross-replica migration, the zero-404 sequence:

        1. **acquire** on the destination (shipping the artifact from
           the source's ``.../artifact`` endpoint when the source is
           reachable; from the destination's own disk otherwise — the
           replica-loss recovery path);
        2. **route** — pin the member's owner to the destination and
           bump the table version, so clients learning the new table go
           to the replica that now definitely owns it, while clients on
           the old table still hit the source, which ALSO still owns it;
        3. **release** on the source (skipped when unreachable).

        Between 1 and 3 the member is dual-owned and both replicas
        answer identically — the migration has no window in which any
        correctly-routed request can 404. Serialized with other moves."""
        async with self._migration_lock:
            table = await self.routing(refresh=True)
            if table is None:
                return {"moved": False, "member": member,
                        "error": "no routing table (no replicas observed)"}
            replicas = table["replicas"]
            if not 0 <= dst < len(replicas):
                return {"moved": False, "member": member,
                        "error": f"unknown destination replica {dst}"}
            if src is None:
                src = table["members"].get(member)
            if src == dst:
                return {"moved": False, "member": member, "src": src,
                        "dst": dst, "error": "member already at destination"}
            prefixes = self._replica_prefixes()
            src_rep = (
                replicas[src] if src is not None and 0 <= src < len(replicas)
                else None
            )
            src_reachable = bool(src_rep and src_rep["reachable"])
            payload: Dict[str, Any] = {"member": member}
            if src_reachable:
                payload["source"] = src_rep["url"]
            timeout = aiohttp.ClientTimeout(total=300)
            verdict: Dict[str, Any] = {
                "member": member, "src": src, "dst": dst,
            }
            async with aiohttp.ClientSession(timeout=timeout) as session:

                async def post(url, body):
                    async def go():
                        async with session.post(url, json=body) as resp:
                            try:
                                return resp.status, await resp.json()
                            except Exception:
                                return resp.status, {}

                    # generous: an acquire pays an artifact ship + bank
                    # build + warm compile before it answers
                    return await Deadline(240.0).wait_for(go())

                try:
                    status, body = await post(
                        prefixes[dst] + "/mesh/acquire", payload
                    )
                except Exception as exc:
                    self._migrations_failed += 1
                    verdict.update(
                        moved=False,
                        error=f"acquire failed: {type(exc).__name__}: {exc}",
                    )
                    self.events.emit(
                        "mesh.migrate_failed",
                        severity="error",
                        member=member,
                        dst=dst,
                        error=verdict["error"],
                    )
                    return verdict
                verdict["acquire"] = {
                    "status": status,
                    "swap": body.get("swap"),
                    "already_owned": bool(body.get("already_owned")),
                }
                if status != 200:
                    self._migrations_failed += 1
                    verdict.update(
                        moved=False,
                        error=f"acquire answered {status}: "
                              f"{body.get('error')}",
                    )
                    self.events.emit(
                        "mesh.migrate_failed",
                        severity="error",
                        member=member,
                        dst=dst,
                        error=verdict["error"],
                    )
                    return verdict
                # destination owns it: flip routing BEFORE the release
                self._bump_routing_owner(member, dst)
                if src is not None and src_reachable:
                    try:
                        status, body = await post(
                            prefixes[src] + "/mesh/release",
                            {"member": member},
                        )
                        verdict["release"] = {
                            "status": status, "swap": body.get("swap"),
                        }
                        if status != 200:
                            # dual ownership persists — safe (both answer);
                            # flagged so the operator retries the release
                            verdict["warning"] = (
                                f"release answered {status}: "
                                f"{body.get('error')} (member dual-owned "
                                "until retried)"
                            )
                    except Exception as exc:
                        verdict["warning"] = (
                            f"release failed: {type(exc).__name__}: {exc} "
                            "(member dual-owned until retried)"
                        )
                else:
                    verdict["release"] = {"skipped": "source unreachable"}
            self._migrations_total += 1
            await self.routing(refresh=True)
            verdict.update(moved=True, routing_version=self._routing_version)
            self.events.emit(
                "mesh.migrate",
                member=member,
                src=src,
                dst=dst,
                dual_owned="warning" in verdict,
            )
            return verdict

    async def fleet_rebalance_cross(
        self, dry_run: bool = False, force: bool = False
    ) -> Dict[str, Any]:
        """The fleet placement tier end-to-end: observe ownership +
        fleet-rolled loads, plan cross-replica moves
        (placement/planner.py::plan_fleet — degraded/burning replicas
        are never move destinations), and apply them move-by-move
        through :meth:`apply_move` (each one a zero-404 acquire ->
        route -> release sequence riding both banks' hot-swaps).
        ``force`` overrides the improvement threshold and the min-rows
        floor, never the health gates."""
        from gordo_components_tpu.placement.planner import plan_fleet

        await self.routing(refresh=True)
        members_by_replica = dict(self._routing_member_lists)
        loads, health = await asyncio.gather(
            self.fleet_loads(), self._replica_health_for_moves()
        )
        plan = plan_fleet(
            members_by_replica,
            loads,
            replica_health=health,
            min_rows=0 if force else self.mesh_min_rows,
        )
        applicable = plan.should_apply or (force and bool(plan.moves))
        if dry_run or not applicable:
            return {
                "applied": 0,
                "dry_run": dry_run,
                "plan": plan.summary(),
                "routing_version": self._routing_version,
            }
        verdicts = []
        applied = 0
        for move in plan.moves:
            verdict = await self.apply_move(move.member, move.dst, src=move.src)
            verdicts.append(verdict)
            if not verdict.get("moved"):
                # a failed acquire aborts the remainder: the plan was
                # computed against an ownership state that just refused
                # to change, and pushing on would compound the drift
                break
            applied += 1
        return {
            "applied": applied,
            "dry_run": False,
            "forced": force and not plan.should_apply,
            "plan": plan.summary(),
            "moves": verdicts,
            "routing_version": self._routing_version,
        }

    async def fleet_slow_traces(self, per_replica: int = 5) -> Dict[str, Any]:
        """Fleet flight-recorder view: each replica's worst recent traces
        (its slow reservoir, slowest first), plus a fleet-wide ``worst``
        list merged across replicas — "which requests were slowest
        ANYWHERE, and on which replica" in one fetch. Best-effort and
        uncached (an operator debugging tool, not a poll target): a
        replica that fails to answer is marked unscraped, never an
        error."""
        urls = self._trace_urls()
        timeout = aiohttp.ClientTimeout(total=30)
        async with aiohttp.ClientSession(timeout=timeout) as session:

            async def fetch(url):
                async def get():
                    async with session.get(
                        url, params={"n": str(per_replica)}
                    ) as resp:
                        if resp.status != 200:
                            return None
                        return await resp.json()

                try:
                    return await Deadline(10.0).wait_for(get())
                except asyncio.CancelledError:
                    raise
                except Exception as exc:
                    logger.debug("trace scrape failed for %s: %s", url, exc)
                    return None

            bodies = await asyncio.gather(*(fetch(u) for u in urls))
        replicas: List[Dict[str, Any]] = []
        worst: List[Dict[str, Any]] = []
        for i, body in enumerate(bodies):
            entry: Dict[str, Any] = {
                "replica": i,
                "scraped": body is not None,
                "tracing_enabled": bool(body and body.get("enabled")),
            }
            if body and body.get("enabled"):
                traces = body.get("traces") or []
                entry["traces"] = traces
                for t in traces:
                    if not isinstance(t, dict):
                        continue
                    worst.append(
                        {
                            "replica": i,
                            **{
                                k: t.get(k)
                                for k in (
                                    "trace_id",
                                    "name",
                                    "request_id",
                                    "duration_ms",
                                    "error",
                                )
                            },
                        }
                    )
            replicas.append(entry)
        worst.sort(key=lambda t: -(t.get("duration_ms") or 0.0))
        return {
            "replicas": replicas,
            "worst": worst[: max(per_replica, 10)],
        }

    async def snapshot(self) -> Dict[str, Any]:
        async with self._lock:
            now = self.clock.monotonic()
            if self._cache is not None and now - self._cache_time < self.refresh_interval:
                return self._cache
            try:
                _FP_SNAPSHOT.fire()
                return await self._refresh_snapshot(now)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                # last-good retention: a refresh that blows up (a peer
                # speaking garbage, a DNS flap, an injected fault) serves
                # the previous snapshot STAMPED stale instead of a 500 —
                # and leaves the cache timestamp alone so the next request
                # retries the refresh immediately
                if self._cache is not None:
                    age = now - self._cache_time
                    logger.error(
                        "watchman snapshot refresh failed (%s); serving "
                        "last-good snapshot (%.0fs old)", exc, age,
                    )
                    stale = dict(self._cache)
                    stale["stale"] = True
                    stale["stale_seconds"] = round(age, 1)
                    stale["refresh_error"] = f"{type(exc).__name__}: {exc}"
                    return stale
                logger.error(
                    "watchman snapshot refresh failed with no last-good "
                    "snapshot to serve", exc_info=True,
                )
                return {
                    "project_name": self.project,
                    "gordo-watchman-version": __version__,
                    "endpoints": [],
                    "error": f"{type(exc).__name__}: {exc}",
                }

    async def _refresh_snapshot(self, now: float) -> Dict[str, Any]:
        """One full snapshot refresh (runs under ``self._lock``)."""
        timeout = aiohttp.ClientTimeout(total=30)
        sem = asyncio.Semaphore(self.parallelism)
        async with aiohttp.ClientSession(timeout=timeout) as session:
            batched = await self._fetch_metadata_all(session)
            if batched is not None:
                # stats is decoration-only: fetch it CONCURRENTLY with
                # the endpoint assembly so a slow /stats can't add its
                # deadline to every cache refresh held under the lock
                (endpoints, bank), stats = await asyncio.gather(
                    self._snapshot_from_batched(session, sem, batched),
                    self._fetch_stats(session),
                )
                return await self._finish_snapshot(
                    endpoints, bank, now, stats
                )
            # /models carries both the target list and the HBM bank
            # coverage (which models score from the stacked bank vs
            # the per-model fallback, and why) — fetched even with an
            # explicit target list so operators see serving coverage
            # fleet-wide. With an explicit list it runs concurrently
            # with the health poll AND under its own short deadline:
            # the outer gather still waits for it, so without the
            # wait_for a hung collection endpoint would stall the
            # refresh by the full 30s client timeout for data that is
            # coverage-only decoration.

            async def fetch_models(deadline: Optional[float] = None):
                async def get():
                    async with session.get(
                        f"{self.base_url}/gordo/v0/{self.project}/models"
                    ) as resp:
                        return await resp.json()

                if deadline is None:
                    return await get()
                return await Deadline(deadline).wait_for(get())

            bank = None
            targets = self.targets
            if targets is None:
                try:
                    body = await fetch_models()
                    bank = body.get("bank")
                    targets = body["models"]
                except Exception as exc:
                    logger.warning("target discovery failed: %s", exc)
                    targets = []
                results = await asyncio.gather(
                    *(self._check_target(session, sem, t) for t in targets)
                )
            else:
                results, models_body = await asyncio.gather(
                    asyncio.gather(
                        *(self._check_target(session, sem, t) for t in targets)
                    ),
                    fetch_models(deadline=10.0),
                    return_exceptions=True,
                )
                if isinstance(results, BaseException):
                    raise results
                if isinstance(models_body, BaseException):
                    # coverage-only fetch: targets are intact, so this
                    # is diagnostic noise, not a discovery failure
                    logger.debug("bank coverage fetch failed: %s", models_body)
                else:
                    bank = models_body.get("bank")
        return await self._finish_snapshot(list(results), bank, now)

    async def _snapshot_from_batched(
        self, session, sem, batched: Dict[str, Any]
    ) -> tuple:
        """Endpoint entries from one ``metadata-all`` response. With an
        explicit target list, targets the collection doesn't know (e.g.
        served by a foreign per-model server behind the same base URL)
        still get individual per-target polls."""
        tmap = batched.get("targets", {})
        targets = self.targets if self.targets is not None else sorted(tmap)
        by_target: Dict[str, Dict[str, Any]] = {}
        missing = []
        for t in targets:
            if t in tmap:
                entry = {
                    "endpoint": f"/gordo/v0/{self.project}/{t}/",
                    "target": t,
                    "healthy": bool(tmap[t].get("healthy", False)),
                }
                for key in ("endpoint-metadata", "digest"):
                    if key in tmap[t]:
                        entry[key] = tmap[t][key]
                by_target[t] = entry
            else:
                missing.append(t)
        if missing:
            polled = await asyncio.gather(
                *(self._check_target(session, sem, t) for t in missing)
            )
            by_target.update({e["target"]: e for e in polled})
        return [by_target[t] for t in targets], batched.get("bank")

    async def _finish_snapshot(
        self,
        endpoints: List[Dict[str, Any]],
        bank,
        now: float,
        stats: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Shared snapshot tail: bank-coverage annotation, gang heartbeat
        aggregation, cache commit. Runs under ``self._lock``."""
        if bank is not None:
            banked = set(bank.get("banked", []))
            fallback = bank.get("fallback", {})
            for entry in endpoints:
                t = entry["target"]
                if t in banked:
                    entry["banked"] = True
                elif t in fallback:
                    entry["banked"] = False
                    entry["bank-fallback-reason"] = fallback[t]
                else:
                    entry["banked"] = None  # not known to the collection
        self._cache = {
            "project_name": self.project,
            "gordo-watchman-version": __version__,
            "endpoints": endpoints,
        }
        if bank is not None:
            self._cache["bank"] = bank
        if stats is not None:
            self._cache["server-stats"] = stats
        if self.gang_state_dir:
            from gordo_components_tpu.workflow.gang_state import read_gang_states

            gangs = await asyncio.get_running_loop().run_in_executor(
                None,
                read_gang_states,
                self.gang_state_dir,
                self.gang_stale_after,
            )
            self._cache["gangs"] = gangs
        self._cache_time = now
        return self._cache


def build_watchman_app(
    project: str,
    base_url: str,
    targets: Optional[List[str]] = None,
    refresh_interval: float = 30.0,
    gang_state_dir: Optional[str] = None,
    full_metadata: bool = False,
    metrics_urls: Optional[List[str]] = None,
    clock=None,
) -> web.Application:
    state = WatchmanState(
        project, base_url, targets, refresh_interval,
        gang_state_dir=gang_state_dir, full_metadata=full_metadata,
        metrics_urls=metrics_urls, clock=clock,
    )
    app = web.Application()
    app["state"] = state

    async def root(request: web.Request) -> web.Response:
        body = dict(await state.snapshot())  # copy: the cache must stay clean
        # the fleet's replica target list (derived from the metrics
        # scrape config), stamped with the routing plane's version +
        # per-replica health/staleness: a hedging or fan-out client can
        # tell a STALE table (watchman stopped observing, or the version
        # moved under it after a rebalance) from a fresh one instead of
        # silently mis-routing. Entries are objects; the bare URL list
        # the pre-mesh snapshot served lives in each entry's "url"
        # (Client.replicas_from_watchman accepts both forms).
        # wait=False: the health path never blocks on a routing rebuild
        table = await state.routing(wait=False)
        if table is not None:
            # the table's own entries: per-replica url/health came from
            # the same observation, so the stamps can never misalign
            body["replicas"] = [
                {
                    "replica": rep["replica"],
                    "url": rep["url"],
                    "routing_version": table["version"],
                    "routing_age_s": table["age_s"],
                    "status": rep["status"],
                    "reachable": rep["reachable"],
                }
                for rep in table["replicas"]
            ]
        else:  # no observation yet: the configured target list
            body["replicas"] = [
                {"replica": i, "url": url}
                for i, url in enumerate(state.replica_base_urls())
            ]
        if table is not None:
            body["routing"] = {
                "version": table["version"],
                "age_s": table["age_s"],
                "stale": table["stale"],
                "members": len(table["members"]),
                "migrating": len(table["migrating"]),
            }
        # bounded fleet-metrics summary rides along so one snapshot answers
        # both "is the fleet healthy" and "is any shard hot anywhere".
        # wait=False: the health path must not inherit a hung replica's
        # scrape timeout — it serves the last rollup and refreshes in the
        # background
        agg = await state.fleet_metrics(wait=False)
        last_success = (agg or {}).get("replica_last_success") or []
        if agg is not None and (
            agg["replicas_scraped"] or any(t is not None for t in last_success)
        ):
            body["fleet-metrics"] = {
                "replicas_scraped": agg["replicas_scraped"],
                "shard_skew_ratio": agg["shard_skew_ratio"],
                "skew_window": agg["skew_window"],
                "routed_rows_by_shard": agg["routed_rows_by_shard"],
                # live per-replica scrape age: ~0 = fresh, large = the
                # rollup is carrying this replica's last-good numbers
                "scrape_stale_seconds": {
                    str(i): round(max(0.0, state.clock.monotonic() - ts), 1)
                    for i, ts in enumerate(last_success)
                    if ts is not None
                },
            }
        # streaming drift/staleness, folded into the health snapshot's
        # degraded calculus: a fleet whose freshest data is older than
        # GORDO_STALENESS_DEGRADED_S (or with members drifted past their
        # thresholds) is serving answers nobody should trust — mark the
        # snapshot degraded with the reason, the same
        # 200-with-status-body contract the server's /healthz uses.
        # wait=False: the health path never blocks on a drift scrape
        drift = await state.fleet_drift(wait=False)
        if drift is not None and drift["replicas_streaming"]:
            body["streaming"] = {
                "drifted": drift["drifted"],
                "worst": drift["worst"],
                "max_staleness_seconds": drift["max_staleness_seconds"],
                "stale_degraded": drift["stale_degraded"],
            }
            if drift["stale_degraded"] or drift["drifted"]:
                body["status"] = "degraded"
                body["degraded_reason"] = (
                    "model staleness above GORDO_STALENESS_DEGRADED_S"
                    if drift["stale_degraded"]
                    else f"{len(drift['drifted'])} member(s) drifted"
                )
        return web.json_response(body)

    async def healthcheck(request: web.Request) -> web.Response:
        return web.json_response({"gordo-watchman-version": __version__})

    async def metrics(request: web.Request) -> web.Response:
        """Fleet-aggregated Prometheus rollup (sum across replicas +
        computed skew gauges) — the one scrape that answers "is any shard
        hot anywhere in the fleet".

        Blocks for a live scrape only when there is no cache yet; after
        that it serves the cache and refreshes in the background — one
        hung replica's 10s scrape timeout must not push THIS endpoint
        past Prometheus' own scrape deadline on every refresh."""
        agg = await state.fleet_metrics(wait=state._metrics_cache is None)
        if agg is None:  # lost the first-scrape race: render an empty rollup
            agg = aggregate_fleet_metrics([])
        extra = []
        if state._routing_cache is not None or state._migrations_total:
            # stability contract (docs/observability.md): the routing
            # plane's version/migration counters, rendered once a table
            # exists so pre-mesh watchmen emit nothing new
            extra = [
                (
                    "gordo_fleet_routing_version", "gauge",
                    "Routing-table version (bumps iff table content "
                    "changed: ownership, health, or migration overlap)",
                    {}, state._routing_version,
                ),
                (
                    "gordo_fleet_migrations_total", "counter",
                    "Cross-replica migrations whose ownership flipped "
                    "(destination acquired + routing repointed); a failed "
                    "release leaves the member dual-owned — visible in "
                    "the routing table's `migrating` map, not here", {},
                    state._migrations_total,
                ),
                (
                    "gordo_fleet_migrations_failed_total", "counter",
                    "Cross-replica migrations that failed at the acquire "
                    "step (ownership unchanged)", {},
                    state._migrations_failed,
                ),
            ]
        return web.Response(
            body=render_fleet_metrics(
                agg, now_mono=state.clock.monotonic(), extra_gauges=extra
            ).encode("utf-8"),
            headers={"Content-Type": "text/plain; version=0.0.4; charset=utf-8"},
        )

    async def traces(request: web.Request) -> web.Response:
        """Fleet slow-trace view: every replica's worst recent traces
        (``?n=`` per replica, default 5) plus the merged fleet-wide
        ``worst`` list — the cross-replica companion to each server's
        ``GET .../traces/slow``."""
        try:
            per_replica = int(request.query.get("n", "5"))
        except ValueError:
            per_replica = -1
        if per_replica < 1:
            raise web.HTTPBadRequest(
                text='{"error": "n must be a positive integer"}',
                content_type="application/json",
            )
        return web.json_response(
            await state.fleet_slow_traces(per_replica=per_replica)
        )

    async def slo(request: web.Request) -> web.Response:
        """Fleet SLO rollup: per-objective/window good+total sums across
        replicas, recomputed fleet burn rates, and per-replica worst-burn
        attribution — "who is burning the fleet's error budget" in one
        fetch. ``?refresh=1`` forces a fresh sample on every replica."""
        refresh = request.query.get("refresh", "").lower() in (
            "1", "true", "yes",
        )
        return web.json_response(await state.fleet_slo(refresh=refresh))

    async def drift(request: web.Request) -> web.Response:
        """Fleet drift rollup: every replica's ``GET /drift`` aggregated
        — drifted members, worst-drift attribution per replica, and the
        fleet's max data staleness. ``?refresh=1`` forces a fresh drift
        sweep on every replica first."""
        refresh = request.query.get("refresh", "").lower() in (
            "1", "true", "yes",
        )
        rollup = await state.fleet_drift(refresh=refresh)
        return web.json_response(rollup)

    def _q_float(request: web.Request, name: str) -> Optional[float]:
        raw = request.query.get(name)
        if raw is None or raw == "":
            return None
        try:
            return float(raw)
        except ValueError:
            raise web.HTTPBadRequest(
                text='{"error": "%s must be a number"}' % name,
                content_type="application/json",
            )

    async def heat(request: web.Request) -> web.Response:
        """Fleet access-heat rollup: summed per-member rates re-ranked
        into one fleet hottest/coldest list, plus summed tier counts
        and per-bucket breakdowns. ``?top=N`` sizes the rankings;
        ``?refresh=1`` forces a fold on every replica first."""
        refresh = request.query.get("refresh", "").lower() in (
            "1", "true", "yes",
        )
        top = _q_float(request, "top")
        return web.json_response(
            await state.fleet_heat(
                top_n=10 if top is None else int(top), refresh=refresh
            )
        )

    async def costs(request: web.Request) -> web.Response:
        """Fleet device-cost rollup: per-bucket tallies summed across
        replicas, MFU/waste recomputed fleet-wide, ranked by wasted
        device time. ``?refresh=1`` forces a fresh join per replica."""
        refresh = request.query.get("refresh", "").lower() in (
            "1", "true", "yes",
        )
        return web.json_response(await state.fleet_costs(refresh=refresh))

    async def history(request: web.Request) -> web.Response:
        """Fleet metric-history rollup: every replica's retained rings,
        attributed per replica. ``?series=a,b&since=&until=&step=``
        forward to each replica's ``GET /history``."""
        raw_series = request.query.get("series")
        series = (
            [s for s in raw_series.split(",") if s] if raw_series else None
        )
        return web.json_response(
            await state.fleet_history(
                series=series,
                since=_q_float(request, "since"),
                until=_q_float(request, "until"),
                step=_q_float(request, "step"),
            )
        )

    async def events(request: web.Request) -> web.Response:
        """Fleet event timeline: every replica's structured events plus
        the watchman's own (migrations), merged on wall time.
        ``?type=a,b&since_wall=&limit=`` filter the merge."""
        raw_types = request.query.get("type")
        types = [t for t in raw_types.split(",") if t] if raw_types else None
        raw_limit = request.query.get("limit")
        try:
            limit = int(raw_limit) if raw_limit else None
        except ValueError:
            raise web.HTTPBadRequest(
                text='{"error": "limit must be an integer"}',
                content_type="application/json",
            )
        return web.json_response(
            await state.fleet_events(
                since_wall=_q_float(request, "since_wall"),
                types=types,
                limit=limit,
            )
        )

    async def incidents(request: web.Request) -> web.Response:
        """The flight-recorder join: SLO-burn episodes detected in the
        fleet's retained history, grouped into incidents, each with the
        ordered event timeline that overlaps it. ``?threshold=`` (burn
        floor, default 1.0) and ``?margin=`` (grouping/attachment window
        seconds, default 30) tune the correlation."""
        return web.json_response(
            await state.fleet_incidents(
                threshold=_q_float(request, "threshold"),
                margin_s=_q_float(request, "margin"),
            )
        )

    async def routing_view(request: web.Request) -> web.Response:
        """The versioned routing table (multi-host serving): member ->
        owning replica + per-replica health. ``ETag``-conditional: pass
        ``If-None-Match`` with the last seen tag and an unchanged table
        answers 304 with no body — the cheap poll loop the fan-out
        client runs. ``?refresh=1`` forces a fresh observation."""
        refresh = request.query.get("refresh", "").lower() in (
            "1", "true", "yes",
        )
        table = await state.routing(refresh=refresh)
        if table is None:
            # no replicas observable yet: an EMPTY fleet is a valid
            # (version-0) table, not an error — clients fall back to
            # their configured base URL
            table = {
                "project": state.project, "version": 0, "members": {},
                "migrating": {}, "replicas": [], "age_s": None,
                "stale": True,
                "refresh_interval": state.refresh_interval,
            }
        etag = f'"routing-v{table["version"]}"'
        if request.headers.get("If-None-Match") == etag:
            return web.Response(status=304, headers={"ETag": etag})
        return web.json_response(table, headers={"ETag": etag})

    async def migrate(request: web.Request) -> web.Response:
        """Operator-driven single-member migration: JSON body
        ``{"member": name, "to": replica_index}`` (optional ``"from"``)
        runs the zero-404 acquire -> route -> release sequence. The
        programmatic form of what ``POST /fleet-rebalance`` does per
        planned move."""
        try:
            body = await request.json()
        except Exception:
            body = None
        if (
            not isinstance(body, dict)
            or not isinstance(body.get("member"), str)
            or not isinstance(body.get("to"), int)
        ):
            raise web.HTTPBadRequest(
                text='{"error": "expected {\\"member\\": \\"<name>\\", '
                     '\\"to\\": <replica index>}"}',
                content_type="application/json",
            )
        src = body.get("from")
        if src is not None and not isinstance(src, int):
            raise web.HTTPBadRequest(
                text='{"error": "from must be a replica index"}',
                content_type="application/json",
            )
        verdict = await state.apply_move(body["member"], body["to"], src=src)
        return web.json_response(
            verdict, status=200 if verdict.get("moved") else 409
        )

    async def fleet_rebalance_cross(request: web.Request) -> web.Response:
        """The fleet placement tier: plan cross-replica ownership moves
        from fleet-rolled routing counters (``?dry_run=1`` previews) and
        apply them through the migration sequence. ``{"force": true}``
        overrides the improvement/min-rows gates — never the health
        gates (a degraded, unreachable, or SLO-burning replica is not a
        valid destination under any flag)."""
        dry_run = request.query.get("dry_run", "").lower() in (
            "1", "true", "yes",
        )
        force = False
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:
                body = None
            if isinstance(body, dict):
                force = bool(body.get("force", False))
        return web.json_response(
            await state.fleet_rebalance_cross(dry_run=dry_run, force=force)
        )

    async def rebalance(request: web.Request) -> web.Response:
        """Fleet rebalance fan-out: forward ``POST /rebalance`` to every
        replica (``?dry_run=1`` previews; JSON body ``{"force": true}``
        forwards the operator override) and aggregate the verdicts."""
        dry_run = request.query.get("dry_run", "").lower() in (
            "1", "true", "yes",
        )
        force = False
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:
                body = None
            if isinstance(body, dict):
                force = bool(body.get("force", False))
        return web.json_response(
            await state.fleet_rebalance(dry_run=dry_run, force=force)
        )

    app.router.add_get("/", root)
    app.router.add_get("/healthcheck", healthcheck)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/traces", traces)
    app.router.add_get("/slo", slo)
    app.router.add_get("/drift", drift)
    app.router.add_get("/heat", heat)
    app.router.add_get("/costs", costs)
    app.router.add_get("/history", history)
    app.router.add_get("/events", events)
    app.router.add_get("/incidents", incidents)
    app.router.add_post("/rebalance", rebalance)
    app.router.add_get("/routing", routing_view)
    app.router.add_post("/migrate", migrate)
    app.router.add_post("/fleet-rebalance", fleet_rebalance_cross)
    return app


def run_watchman(
    project: str,
    base_url: str,
    targets: Optional[List[str]] = None,
    host: str = "0.0.0.0",
    port: int = 5556,
    refresh_interval: float = 30.0,
    gang_state_dir: Optional[str] = None,
    full_metadata: bool = False,
    metrics_urls: Optional[List[str]] = None,
) -> None:
    web.run_app(
        build_watchman_app(
            project, base_url, targets, refresh_interval,
            gang_state_dir=gang_state_dir, full_metadata=full_metadata,
            metrics_urls=metrics_urls,
        ),
        host=host,
        port=port,
    )
