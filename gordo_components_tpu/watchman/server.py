"""Watchman service.

Reference parity: gordo_components/watchman/server.py (unverified;
SURVEY.md §2 "watchman", §3.5) — the in-tree fleet failure *detector*: for a
project's target list, poll each model server's ``/healthcheck`` and
``/metadata`` and serve the aggregate
``{project_name, endpoints: [{endpoint, healthy, metadata}, ...]}``.

TPU-native notes: with the collection server, many targets share one base
URL; a snapshot costs ONE request to the batched ``metadata-all``
control-plane endpoint (with reference-style per-target polling, bounded
concurrency, as the fallback for foreign servers and for explicit targets
the collection doesn't know). Watchman discovers targets from ``GET
/models`` when no explicit list is given. Results are cached for
``refresh_interval`` seconds.
"""

import asyncio
import logging
import time
from typing import Any, Dict, List, Optional

import aiohttp
from aiohttp import web

from gordo_components_tpu import __version__

logger = logging.getLogger(__name__)


class WatchmanState:
    def __init__(
        self,
        project: str,
        base_url: str,
        targets: Optional[List[str]] = None,
        refresh_interval: float = 30.0,
        parallelism: int = 20,
        gang_state_dir: Optional[str] = None,
        gang_stale_after: float = 120.0,
        full_metadata: bool = False,
    ):
        self.project = project
        self.base_url = base_url.rstrip("/")
        self.targets = targets
        self.refresh_interval = refresh_interval
        self.parallelism = parallelism
        # digest polling by default (VERDICT r3 next #5): a 10k-model
        # snapshot with per-epoch training histories is tens of MB of JSON
        # encoded on the SERVING process every refresh; the digest keeps
        # the control plane O(small) bytes. full_metadata restores the
        # reference-style full aggregate on request.
        self.full_metadata = bool(full_metadata)
        # builder-side failure detection: aggregate gang heartbeats from
        # the shared state volume (workflow/gang_state.py) so a stalled or
        # failed TPU gang is visible next to serving health
        self.gang_state_dir = gang_state_dir
        self.gang_stale_after = gang_stale_after
        self._cache: Optional[Dict[str, Any]] = None
        self._cache_time = 0.0
        self._lock = asyncio.Lock()

    def _url(self, target: str, endpoint: str) -> str:
        return f"{self.base_url}/gordo/v0/{self.project}/{target}/{endpoint}"

    async def _check_target(self, session, sem, target: str) -> Dict[str, Any]:
        entry: Dict[str, Any] = {
            "endpoint": f"/gordo/v0/{self.project}/{target}/",
            "target": target,
            "healthy": False,
        }
        async with sem:
            try:
                async with session.get(self._url(target, "healthcheck")) as resp:
                    entry["healthy"] = resp.status == 200
                if entry["healthy"]:
                    async with session.get(self._url(target, "metadata")) as resp:
                        if resp.status == 200:
                            body = await resp.json()
                            meta = body.get("endpoint-metadata", {})
                            if self.full_metadata:
                                entry["endpoint-metadata"] = meta
                            else:
                                # foreign servers only speak full metadata;
                                # digest locally so the snapshot shape is
                                # uniform across the batched and fallback
                                # paths
                                from gordo_components_tpu.utils.digest import (
                                    metadata_digest,
                                )

                                entry["digest"] = metadata_digest(meta)
            except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
                logger.warning("healthcheck failed for %s: %s", target, exc)
        return entry

    async def _fetch_metadata_all(self, session) -> Optional[Dict[str, Any]]:
        """The collection server's batched control-plane endpoint: every
        target's health + metadata in ONE request (O(1) per snapshot
        instead of O(2N) per-target polls hammering the process that also
        serves scoring traffic). Returns None when the server doesn't
        speak it, so foreign per-model servers keep working via the
        per-target fallback (shared deadline + shape-validation contract:
        client/io.py::fetch_metadata_all)."""
        from gordo_components_tpu.client.io import fetch_metadata_all

        return await fetch_metadata_all(
            session, self.base_url, self.project,
            digest=not self.full_metadata,
        )

    async def _fetch_stats(self, session) -> Optional[Dict[str, Any]]:
        """Serving-load counters from the collection's ``/stats`` — a
        best-effort decoration (collection servers only; foreign servers
        simply lack it) so operators see request/coalescing load next to
        fleet health."""

        async def get():
            async with session.get(
                f"{self.base_url}/gordo/v0/{self.project}/stats"
            ) as resp:
                if resp.status != 200:
                    return None
                return await resp.json()

        try:
            body = await asyncio.wait_for(get(), timeout=10.0)
        except (aiohttp.ClientError, asyncio.TimeoutError, ValueError) as exc:
            logger.debug("stats fetch failed: %s", exc)
            return None
        return body if isinstance(body, dict) else None

    async def snapshot(self) -> Dict[str, Any]:
        async with self._lock:
            now = time.monotonic()
            if self._cache is not None and now - self._cache_time < self.refresh_interval:
                return self._cache
            timeout = aiohttp.ClientTimeout(total=30)
            sem = asyncio.Semaphore(self.parallelism)
            async with aiohttp.ClientSession(timeout=timeout) as session:
                batched = await self._fetch_metadata_all(session)
                if batched is not None:
                    # stats is decoration-only: fetch it CONCURRENTLY with
                    # the endpoint assembly so a slow /stats can't add its
                    # deadline to every cache refresh held under the lock
                    (endpoints, bank), stats = await asyncio.gather(
                        self._snapshot_from_batched(session, sem, batched),
                        self._fetch_stats(session),
                    )
                    return await self._finish_snapshot(
                        endpoints, bank, now, stats
                    )
                # /models carries both the target list and the HBM bank
                # coverage (which models score from the stacked bank vs
                # the per-model fallback, and why) — fetched even with an
                # explicit target list so operators see serving coverage
                # fleet-wide. With an explicit list it runs concurrently
                # with the health poll AND under its own short deadline:
                # the outer gather still waits for it, so without the
                # wait_for a hung collection endpoint would stall the
                # refresh by the full 30s client timeout for data that is
                # coverage-only decoration.

                async def fetch_models(deadline: Optional[float] = None):
                    async def get():
                        async with session.get(
                            f"{self.base_url}/gordo/v0/{self.project}/models"
                        ) as resp:
                            return await resp.json()

                    if deadline is None:
                        return await get()
                    return await asyncio.wait_for(get(), timeout=deadline)

                bank = None
                targets = self.targets
                if targets is None:
                    try:
                        body = await fetch_models()
                        bank = body.get("bank")
                        targets = body["models"]
                    except Exception as exc:
                        logger.warning("target discovery failed: %s", exc)
                        targets = []
                    results = await asyncio.gather(
                        *(self._check_target(session, sem, t) for t in targets)
                    )
                else:
                    results, models_body = await asyncio.gather(
                        asyncio.gather(
                            *(self._check_target(session, sem, t) for t in targets)
                        ),
                        fetch_models(deadline=10.0),
                        return_exceptions=True,
                    )
                    if isinstance(results, BaseException):
                        raise results
                    if isinstance(models_body, BaseException):
                        # coverage-only fetch: targets are intact, so this
                        # is diagnostic noise, not a discovery failure
                        logger.debug("bank coverage fetch failed: %s", models_body)
                    else:
                        bank = models_body.get("bank")
            return await self._finish_snapshot(list(results), bank, now)

    async def _snapshot_from_batched(
        self, session, sem, batched: Dict[str, Any]
    ) -> tuple:
        """Endpoint entries from one ``metadata-all`` response. With an
        explicit target list, targets the collection doesn't know (e.g.
        served by a foreign per-model server behind the same base URL)
        still get individual per-target polls."""
        tmap = batched.get("targets", {})
        targets = self.targets if self.targets is not None else sorted(tmap)
        by_target: Dict[str, Dict[str, Any]] = {}
        missing = []
        for t in targets:
            if t in tmap:
                entry = {
                    "endpoint": f"/gordo/v0/{self.project}/{t}/",
                    "target": t,
                    "healthy": bool(tmap[t].get("healthy", False)),
                }
                for key in ("endpoint-metadata", "digest"):
                    if key in tmap[t]:
                        entry[key] = tmap[t][key]
                by_target[t] = entry
            else:
                missing.append(t)
        if missing:
            polled = await asyncio.gather(
                *(self._check_target(session, sem, t) for t in missing)
            )
            by_target.update({e["target"]: e for e in polled})
        return [by_target[t] for t in targets], batched.get("bank")

    async def _finish_snapshot(
        self,
        endpoints: List[Dict[str, Any]],
        bank,
        now: float,
        stats: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Shared snapshot tail: bank-coverage annotation, gang heartbeat
        aggregation, cache commit. Runs under ``self._lock``."""
        if bank is not None:
            banked = set(bank.get("banked", []))
            fallback = bank.get("fallback", {})
            for entry in endpoints:
                t = entry["target"]
                if t in banked:
                    entry["banked"] = True
                elif t in fallback:
                    entry["banked"] = False
                    entry["bank-fallback-reason"] = fallback[t]
                else:
                    entry["banked"] = None  # not known to the collection
        self._cache = {
            "project_name": self.project,
            "gordo-watchman-version": __version__,
            "endpoints": endpoints,
        }
        if bank is not None:
            self._cache["bank"] = bank
        if stats is not None:
            self._cache["server-stats"] = stats
        if self.gang_state_dir:
            from gordo_components_tpu.workflow.gang_state import read_gang_states

            gangs = await asyncio.get_running_loop().run_in_executor(
                None,
                read_gang_states,
                self.gang_state_dir,
                self.gang_stale_after,
            )
            self._cache["gangs"] = gangs
        self._cache_time = now
        return self._cache


def build_watchman_app(
    project: str,
    base_url: str,
    targets: Optional[List[str]] = None,
    refresh_interval: float = 30.0,
    gang_state_dir: Optional[str] = None,
    full_metadata: bool = False,
) -> web.Application:
    state = WatchmanState(
        project, base_url, targets, refresh_interval,
        gang_state_dir=gang_state_dir, full_metadata=full_metadata,
    )
    app = web.Application()
    app["state"] = state

    async def root(request: web.Request) -> web.Response:
        return web.json_response(await state.snapshot())

    async def healthcheck(request: web.Request) -> web.Response:
        return web.json_response({"gordo-watchman-version": __version__})

    app.router.add_get("/", root)
    app.router.add_get("/healthcheck", healthcheck)
    return app


def run_watchman(
    project: str,
    base_url: str,
    targets: Optional[List[str]] = None,
    host: str = "0.0.0.0",
    port: int = 5556,
    refresh_interval: float = 30.0,
    gang_state_dir: Optional[str] = None,
    full_metadata: bool = False,
) -> None:
    web.run_app(
        build_watchman_app(
            project, base_url, targets, refresh_interval,
            gang_state_dir=gang_state_dir, full_metadata=full_metadata,
        ),
        host=host,
        port=port,
    )
