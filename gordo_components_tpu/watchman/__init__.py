"""Watchman: fleet-health aggregation service (reference parity:
gordo_components/watchman/, unverified — SURVEY.md §2, §3.5)."""

from gordo_components_tpu.watchman.server import build_watchman_app, run_watchman

__all__ = ["build_watchman_app", "run_watchman"]
