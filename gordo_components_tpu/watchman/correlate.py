"""Incident correlation: SLO-burn episodes x the fleet event timeline.

The server-side flight recorder retains two axes per replica — metric
history (observability/timeseries.py) and the structured event log
(observability/events.py). This module is the watchman-side join: find
the windows where a replica's ``gordo_slo_burn_rate`` history ran above
threshold (**episodes**), group overlapping episodes fleet-wide into
**incidents**, and attach every event that falls inside the incident's
window (plus a margin) as an ordered, rendered timeline. The result is
the two-clicks-from-spike story extended from one request (tracing,
PR 3) to the whole fleet: ``GET /incidents`` answers "what burned, when,
and what else happened around it" without an operator replaying four
dashboards side by side.

Pure functions over plain data (points are ``[[t, v|None], ...]``,
events are the dicts ``GET /events`` serves) — unit-testable without a
fleet, and reusable by the replay harness for per-scenario timelines.
"""

from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "burn_episodes",
    "group_incidents",
    "render_timeline",
]

# budget burning faster than it accrues — the classic multi-window SLO
# alert floor, NOT the page-now fast-burn threshold (14.4): an incident
# record should cover the whole degradation, not only its peak
DEFAULT_BURN_THRESHOLD = 1.0


def burn_episodes(
    points: Sequence[Sequence[Any]],
    threshold: float = DEFAULT_BURN_THRESHOLD,
    min_points: int = 1,
) -> List[Dict[str, Any]]:
    """Maximal runs of ``value >= threshold`` in one series' points.

    A ``None``/missing value ends the current run (absence of evidence
    is not evidence of burning). Runs shorter than ``min_points`` are
    dropped — one hot sample is noise, the same lesson the canary
    window judge applies."""
    episodes: List[Dict[str, Any]] = []
    run: List[Tuple[float, float]] = []

    def flush():
        if len(run) >= min_points:
            episodes.append(
                {
                    "start": run[0][0],
                    "end": run[-1][0],
                    "peak": max(v for _, v in run),
                    "points": len(run),
                }
            )
        run.clear()

    for pt in points:
        t, v = pt[0], pt[1]
        if v is not None and v >= threshold:
            run.append((float(t), float(v)))
        else:
            flush()
    flush()
    return episodes


def group_incidents(
    episodes: List[Dict[str, Any]],
    events: Optional[List[Dict[str, Any]]] = None,
    margin_s: float = 30.0,
) -> List[Dict[str, Any]]:
    """Merge overlapping/adjacent episodes (within ``margin_s``) into
    incident records and attach the events whose wall time falls inside
    each incident's margin-padded window, oldest first.

    Each input episode may carry ``series``/``replica`` tags (added by
    the caller); the incident unions them so the record names every
    objective and replica that burned."""
    if not episodes:
        return []
    ordered = sorted(episodes, key=lambda e: (e["start"], e["end"]))
    groups: List[List[Dict[str, Any]]] = [[ordered[0]]]
    for ep in ordered[1:]:
        cur = groups[-1]
        if ep["start"] <= max(e["end"] for e in cur) + margin_s:
            cur.append(ep)
        else:
            groups.append([ep])
    incidents: List[Dict[str, Any]] = []
    for i, grp in enumerate(groups):
        start = min(e["start"] for e in grp)
        end = max(e["end"] for e in grp)
        attached = [
            ev
            for ev in (events or ())
            if start - margin_s <= float(ev.get("wall", 0)) <= end + margin_s
        ]
        attached.sort(key=lambda ev: (float(ev.get("wall", 0)), ev.get("seq", 0)))
        incidents.append(
            {
                "id": i,
                "start": start,
                "end": end,
                "duration_s": round(end - start, 3),
                "peak_burn": max(e["peak"] for e in grp),
                "episodes": [
                    {k: v for k, v in e.items() if k != "points"} for e in grp
                ],
                "series": sorted(
                    {e["series"] for e in grp if e.get("series")}
                ),
                "replicas": sorted(
                    {e["replica"] for e in grp if e.get("replica") is not None}
                ),
                "events": attached,
                "timeline": render_timeline(start, attached),
            }
        )
    return incidents


def render_timeline(start: float, events: List[Dict[str, Any]]) -> List[str]:
    """Human-readable one-line-per-event rendering, offsets relative to
    the incident's start (negative = led up to it)."""
    lines: List[str] = []
    for ev in events:
        offset = float(ev.get("wall", 0)) - start
        attrs = ev.get("attrs") or {}
        detail = " ".join(f"{k}={attrs[k]}" for k in sorted(attrs))
        who = ev.get("replica") or "fleet"
        lines.append(
            f"{offset:+9.2f}s [{ev.get('severity', 'info'):7s}] "
            f"{who}: {ev.get('type')}"
            + (f" ({detail})" if detail else "")
        )
    return lines
